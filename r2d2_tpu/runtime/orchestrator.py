"""System bring-up: the reference's ``train()`` (/root/reference/train.py:21-66)
without Ray.

Per player (1, or ``num_players`` complete stacks for multiplayer self-play):
one Learner on the TPU, a weight service, a block queue, and N actors on host
CPUs with the Ape-X ε ladder. Actors start first; training begins once the
buffer passes ``learning_starts`` (the reference polls buffer.ready,
train.py:49-54); the driver loop logs every ``log_interval`` seconds.

Actor modes:
  * "thread"  — actors are threads with CPU-pinned jitted policies; hermetic,
    used by tests and single-host quickstarts.
  * "process" — spawned OS processes (the reference's Ray-actor equivalent):
    JAX_PLATFORMS=cpu children, shared-memory weight reads, mp.Queue blocks.

Multiplayer wiring mirrors train.py:28-45: actor i of player 0 hosts game i
on port base+i; actor i of every other player joins that game.
"""

import multiprocessing as mp
import os
import signal
import threading
import time
from typing import Callable, List, Optional

from r2d2_tpu.config import Config, apex_epsilon
from r2d2_tpu.envs.factory import create_env
from r2d2_tpu.models.network import NetworkApply
from r2d2_tpu.runtime.actor_loop import make_actor_env, make_actor_policy
from r2d2_tpu.runtime.actor_main import actor_process_main
from r2d2_tpu.runtime.feeder import BlockQueue
from r2d2_tpu.runtime.learner_loop import Learner
from r2d2_tpu.runtime.metrics import TrainMetrics
from r2d2_tpu.runtime.weights import (InProcWeightStore, WeightPublisher,
                                      make_publish_preparer, wrap_publish)


class _VacantSlot:
    """Placeholder worker for a spare membership slot (ISSUE 15): keeps
    the worker lists index-aligned with the slot table so a joiner can
    land in ANY leased slot. Never alive; supervision skips it anyway
    (spare slots are health-detached until adopted)."""

    def is_alive(self) -> bool:
        return False


class PlayerStack:
    """One player's buffer+learner+actors (the reference creates these per
    player in train.py:28-45)."""

    def __init__(self, cfg: Config, player_idx: int, action_dim: int):
        self.cfg = cfg
        self.player_idx = player_idx
        self.net = NetworkApply(action_dim, cfg.network, cfg.env.frame_stack,
                                cfg.env.frame_height, cfg.env.frame_width)
        self.metrics = TrainMetrics(player_idx, cfg.runtime.save_dir,
                                    resume=bool(cfg.runtime.resume))
        # unified telemetry (ISSUE 4): ONE Telemetry for this process
        # (learner threads + thread actors observe straight into it);
        # process actors publish through the shm board, which the
        # aggregator differences per log interval. Attached to metrics
        # BEFORE Learner construction so the learner's stage observes
        # never land in the NULL sink; the board's shm allocation happens
        # at the END of __init__ so nothing can raise past a live segment.
        from r2d2_tpu.telemetry import Telemetry
        self.telemetry = Telemetry.from_config(
            cfg, name=f"learner-p{player_idx}")
        self.tele_board = None
        self.metrics.set_telemetry(self.telemetry)
        self.learner = Learner(cfg, self.net, player_idx, metrics=self.metrics)
        self.threads: List[threading.Thread] = []
        self.processes: List[mp.Process] = []
        from r2d2_tpu.runtime.feeder import (
            HeartbeatBoard, IngestStallDetector, RingRecoveryScheduler,
            WorkerHealth)
        self._seen_dead: set = set()    # reaped dead process objects
        self._ring_recovery = RingRecoveryScheduler()
        # elastic membership (ISSUE 15): the slot table spans the
        # fleet's MAX width (fleet.max_slots spare slots lease-able by
        # joiners); the heartbeat board / health policy / telemetry
        # board size to it so an adopted spare publishes through the
        # same rows the startup fleet does. Default config: n_slots ==
        # num_actors and everything below is byte-identical to PR14.
        self.n_slots = cfg.fleet.resolved_max_slots(cfg.actor.num_actors)
        from r2d2_tpu.fleet.membership import FleetMembership
        self.membership = FleetMembership(
            self.n_slots, cfg.actor.envs_per_actor,
            initial_active=cfg.actor.num_actors,
            num_shards=max(cfg.fleet.replay_shards, 1))
        # worker-health subsystem: per-slot heartbeats + the shared
        # watchdog/backoff/breaker policy (feeder.py) + the learner-side
        # ingest stall detector
        self.heartbeats = HeartbeatBoard(self.n_slots)
        self.health = WorkerHealth.from_runtime(
            self.n_slots, self.heartbeats, cfg.runtime)
        for spare in range(cfg.actor.num_actors, self.n_slots):
            # spare slots carry no worker until a joiner leases them —
            # supervision must neither hang-check nor respawn them
            self.health.detach(spare)
        self._stall = IngestStallDetector(cfg.runtime.ingest_stall_timeout_s)
        # grammar-scheduled joins (tools/chaos.py join@t=S): admitted by
        # supervise() once the slot is parked/free and t has elapsed
        from r2d2_tpu.tools.chaos import parse_join_spec
        self._join_schedule = (parse_join_spec(cfg.actor.fault_spec)
                               if cfg.actor.fault_spec else {})
        self._joins_done: set = set()
        self._run_start = time.time()
        # weight fan-out tree (ISSUE 15): built by the actor spawners
        # when fleet.fanout_degree >= 2 (in-proc relays in thread mode,
        # shm relay segments in process mode)
        self._fanout = None
        self._shm_fanout = None
        self._actor_mode = None
        # replay-service socket rung: remote producers route blocks in
        self._service_server = None
        if (cfg.fleet.service_transport == "socket"
                and self.learner.service is not None):
            from r2d2_tpu.fleet.replay_service import ReplayServiceServer
            self._service_server = ReplayServiceServer(
                self.learner.service, cfg.fleet.service_host,
                cfg.fleet.service_port)
        # fleet telemetry: the record's replay_service block (per-shard
        # fill, spill health, fan-out lag, membership leases) — attached
        # only when a fleet plane is configured on, so legacy records
        # stay byte-identical to the PR14 schema
        if cfg.fleet.active and cfg.telemetry.enabled:
            self.metrics.set_replay_service(self._replay_service_block)
        # crash-recovery plane (ISSUE 18): the record's recovery block
        # (snapshot age/bytes/durations, restore counts, at-risk blocks,
        # supervisor restarts) — attached only when the snapshot plane
        # is on, so plane-off records stay byte-identical to PR17
        if cfg.telemetry.enabled and cfg.runtime.snapshot_interval > 0:
            self.metrics.set_recovery(self.learner.recovery_block)
        # last replay-service re-announcement (ISSUE 18): a restarted
        # standalone service posts its address here through the lease
        # board; 'info' callers (joining producers) dial the survivor
        self._replay_announce = None
        self.publisher = None
        self.store = None
        self.queue: Optional[BlockQueue] = None
        self.resources = None
        self.compile_monitor = None
        self.sentinel = None
        # central policy inference service (ISSUE 13): in server mode the
        # stack owns ONE PolicyServer + its endpoint/stats; the endpoint
        # and transports OUTLIVE server restarts (the chaos drill swaps
        # only the server object via restart_serve_server). The stats
        # aggregator is shared with in-proc clients so the periodic
        # record's 'serving' block carries CLIENT-visible latencies.
        self.serve_stats = None
        self.serve_endpoint = None
        self.serve_server = None
        # serving fleet (ISSUE 17): serve.servers > 1 swaps the ONE
        # PolicyServer for a ServerFleet (per-server cache slices behind
        # the shard→server router); the shared stats aggregator and the
        # construction entry points are unchanged, so the single-server
        # path stays byte-identical
        self.serve_fleet = None
        self._serve_transport = None
        self._serve_fleet_transports = []
        self._serve_weight_sub = None
        self._serve_weight_subs = []
        self._serve_weight_poll = None
        self._serve_weight_poll_factory = None
        self._serve_weight_version = None
        self._serve_weight_version_factory = None
        self._serve_copy_updates = True
        self._serve_client_timed = True
        self._serve_spec = None
        self._lease_server = None
        if cfg.actor.inference == "server":
            from r2d2_tpu.serve import InprocEndpoint, ServingStats
            self.serve_stats = ServingStats()
            if cfg.telemetry.enabled and cfg.telemetry.tracing_enabled:
                from r2d2_tpu.telemetry.tracing import ServeTrace
                self.serve_stats.trace = ServeTrace()
            self.serve_endpoint = InprocEndpoint()
            self.metrics.set_serving(self._serving_block)
        # quantized inference plane (ISSUE 14): the publish-time
        # quantizer (None at "f32" — the weight plumbing is then
        # byte-identical to PR13) and the accuracy-probe aggregator
        # feeding the record's 'quant' block. Thread actors and the
        # policy server share ONE QuantStats; process actors run the
        # quantized forward from the same published twin but probe-free
        # (their probe results have no channel back to this record —
        # served inference probes server-side instead).
        self._publish_prep = make_publish_preparer(self.net)
        self.quant_stats = None
        if cfg.network.inference_dtype != "f32":
            from r2d2_tpu.telemetry import QuantStats
            self.quant_stats = QuantStats(
                cfg.network.inference_dtype,
                cfg.telemetry.quant_probe_interval)
            self.metrics.set_quant(self.quant_stats.interval_block)
        # policy-quality plane (ISSUE 20): the quality aggregator + the
        # quality_player{p}.jsonl ledger feeding the record's 'quality'
        # block; the background evaluator and the promotion manager are
        # built by the actor spawners once the weight store exists.
        # Default-off: records stay byte-identical to the PR-19 schema.
        self.quality_stats = None
        self.quality_ledger = None
        self.quality_evaluator = None
        self.promotion = None
        self.shadow = None
        self._shadow_mirror = None
        self._routing_channels: List = []
        if cfg.telemetry.enabled and cfg.telemetry.quality_enabled:
            from r2d2_tpu.telemetry import QualityLedger, QualityStats
            self.quality_stats = QualityStats()
            try:
                self.quality_ledger = QualityLedger(
                    self.quality_stats, cfg.runtime.save_dir or ".",
                    player_idx, resume=bool(cfg.runtime.resume))
            except BaseException:
                self.heartbeats.close()
                raise
            self.metrics.set_quality(self.quality_ledger.interval_block)
        # LAST: telemetry board shm + the span-drain's file I/O. Anything
        # raising after an shm allocation would leak the segment (train()
        # only closes stacks that made it into its list), so the file I/O
        # is guarded to unwind BOTH boards created above.
        if cfg.telemetry.enabled:
            from r2d2_tpu.telemetry import TelemetryBoard
            self.tele_board = TelemetryBoard(self.n_slots)
            self.telemetry.attach_board(self.tele_board)
            try:
                resume = bool(cfg.runtime.resume)
                save_dir = cfg.runtime.save_dir or "."
                if not resume:
                    # fresh run: clear stale actor span files from a
                    # previous run of this save_dir (actor processes
                    # APPEND so respawns keep their predecessors' spans —
                    # this is the one place that truncates, once per run)
                    import glob
                    for stale in glob.glob(os.path.join(
                            save_dir, f"spans_p{player_idx}_a*.jsonl")):
                        try:
                            os.remove(stale)
                        except OSError:
                            pass
                self.telemetry.start_drain(
                    os.path.join(save_dir,
                                 f"spans_player{player_idx}.jsonl"),
                    append=resume)
            except BaseException:
                self.tele_board.close()
                self.heartbeats.close()
                raise
        # system-health pillar (ISSUE 7): resource sampler + compile/
        # retrace monitor + the alert engine, all behind the
        # telemetry.resources_enabled kill switch — off, none of the
        # three exists and the periodic record stays byte-identical to
        # the pre-PR7 schema. The Learner registered its buffer
        # footprints during construction above; the sampler reads the
        # shared registry and the actor gauges off the telemetry board.
        # Compile events are process-global, so only the FIRST stack of a
        # multiplayer process installs the monitor. Wired LAST (the alert
        # stream truncation is file I/O): a failure here must unwind the
        # shm segments allocated above.
        if cfg.telemetry.enabled and cfg.telemetry.resources_enabled:
            from r2d2_tpu.telemetry import (AlertEngine, CompileMonitor,
                                            ResourceMonitor, active_monitor,
                                            default_rules)
            try:
                if (cfg.telemetry.compile_enabled
                        and active_monitor() is None):
                    self.compile_monitor = CompileMonitor().install()
                self.resources = ResourceMonitor(
                    player_idx, cfg.runtime.save_dir or ".",
                    interval_s=cfg.telemetry.resources_interval_s,
                    headroom_warn_frac=(
                        cfg.telemetry.resources_headroom_warn_frac),
                    board=self.tele_board,
                    compile_monitor=self.compile_monitor,
                    aot_coverage_fn=self.learner.aot_coverage)
                self.metrics.set_resources(self.resources.block)
                if cfg.telemetry.alerts_enabled:
                    self.sentinel = AlertEngine(
                        default_rules(cfg.telemetry),
                        jsonl_path=os.path.join(
                            cfg.runtime.save_dir or ".",
                            f"alerts_player{player_idx}.jsonl"),
                        resume=bool(cfg.runtime.resume))
                    self.metrics.set_sentinel(self.sentinel)
            except BaseException:
                if self.compile_monitor is not None:
                    self.compile_monitor.uninstall()
                if self.tele_board is not None:
                    self.tele_board.close()
                self.heartbeats.close()
                raise

    def actor_env_args(self, actor_idx: int):
        """Multiplayer host/join wiring (ref train.py:33-38; shared with
        the per-player-job multihost path via MultiplayerConfig.env_args)."""
        return self.cfg.multiplayer.env_args(self.player_idx, actor_idx)

    def _serving_block(self):
        """Periodic-record 'serving' block provider: the fleet's
        aggregate (shared stats + per-server rows) when serving is
        sharded, the single server's stats otherwise — same schema for
        everything that existed before the fleet."""
        if self.serve_fleet is not None:
            return self.serve_fleet.interval_block(
                deadline_ms=self.cfg.serve.deadline_ms,
                max_batch=self.cfg.serve.max_batch)
        return self.serve_stats.interval_block(
            deadline_ms=self.cfg.serve.deadline_ms,
            max_batch=self.cfg.serve.max_batch)

    def _start_serve_server(self) -> None:
        """(Re)build the serving plane against persistent endpoints —
        the ONE construction path for cold start and the chaos drill's
        restart (the replacement adopts the learner's CURRENT params and
        the same weight-service reader). serve.servers > 1 builds the
        sharded ServerFleet (ISSUE 17) instead of one PolicyServer; the
        default leaves this path byte-identical to the single-server
        plane."""
        if self.cfg.serve.servers > 1:
            from r2d2_tpu.serve import ServerFleet
            self.serve_fleet = ServerFleet(
                self.cfg, self.net, self.learner.train_state.params,
                stats=self.serve_stats, telemetry=self.telemetry,
                client_timed=self._serve_client_timed,
                weight_poll_factory=self._serve_weight_poll_factory,
                weight_version=self._serve_weight_version,
                weight_version_factory=self._serve_weight_version_factory,
                copy_updates=self._serve_copy_updates,
                quant_stats=self.quant_stats)
            return
        from r2d2_tpu.serve import PolicyServer
        self.serve_server = PolicyServer(
            self.cfg, self.net, self.learner.train_state.params,
            endpoint=self.serve_endpoint,
            weight_poll=self._serve_weight_poll,
            weight_version=self._serve_weight_version,
            copy_updates=self._serve_copy_updates,
            stats=self.serve_stats, telemetry=self.telemetry,
            client_timed=self._serve_client_timed,
            quant_stats=self.quant_stats).start()

    def restart_serve_server(self) -> None:
        """Replace a (possibly dead) server with a fresh one on the same
        endpoint; connected clients reconnect transparently (their
        retries drain into the replacement; the lost state cache resets
        served episodes to the episode-initial state, the same grace as
        an eviction). In fleet mode the chaos drill targets individual
        servers through kill/supervise instead — a full restart rebuilds
        the whole fleet."""
        if self.serve_fleet is not None:
            self.serve_fleet.stop()
            self.serve_fleet = None
        if self.serve_server is not None:
            self.serve_server.stop()
        self._start_serve_server()

    def install_shadow(self, candidate_channel, *,
                       sample_rate: Optional[float] = None, seed: int = 0):
        """Shadow-score a candidate server (ISSUE 20): mirror a sampled
        fraction of every routed live request batch to
        ``candidate_channel`` and feed greedy-agreement divergence into
        the quality block — the evidence ``PromotionManager.decide``
        gates on. Installs on every existing router AND every router
        spawned later; candidate replies never reach clients."""
        if self.quality_stats is None:
            raise RuntimeError("shadow scoring needs telemetry."
                               "quality_enabled (the quality plane)")
        if self.shadow is not None:
            raise RuntimeError("a shadow scorer is already installed — "
                               "clear_shadow() first")
        from r2d2_tpu.fleet.promotion import ShadowScorer
        rate = (self.cfg.serve.shadow_sample_rate
                if sample_rate is None else float(sample_rate))
        self.shadow = ShadowScorer(candidate_channel, self.quality_stats,
                                   sample_rate=rate, seed=seed).start()
        self._shadow_mirror = self.shadow.mirror
        for ch in self._routing_channels:
            ch.set_mirror(self._shadow_mirror)
        return self.shadow

    def clear_shadow(self) -> None:
        """Uninstall the shadow tap (promotion decided either way)."""
        if self.shadow is None:
            return
        for ch in self._routing_channels:
            ch.set_mirror(None)
        self._shadow_mirror = None
        self.shadow.stop()
        self.shadow = None

    def start_actors_threads(self, stop: threading.Event) -> None:
        cfg = self.cfg
        prep = self._publish_prep
        params0 = self.learner.train_state.params
        # quant mode publishes the inference bundle (f32 + twin + stamp)
        # through the SAME store; construction counts as publication 1.
        # Thread policies take their initial tree from store.current()
        # (one shared prepared tree, fresh across respawns)
        self.store = InProcWeightStore(
            prep(params0, 1) if prep else params0)
        publish = wrap_publish(
            self.store.publish, prep, lambda: self.store.publish_count)
        # weight fan-out tree (ISSUE 15): the learner publishes ONCE to
        # the root store; in-proc relays re-publish and each actor slot
        # reads its leaf relay — the root sees <= degree readers no
        # matter the fleet width. The published tree (incl. the stamped
        # quant bundle) rides through relays unchanged.
        if cfg.fleet.fanout_degree >= 2:
            from r2d2_tpu.fleet.fanout import FanoutTree
            self._fanout = FanoutTree(
                self.store, self.n_slots, cfg.fleet.fanout_degree,
                pull_interval_s=cfg.fleet.fanout_pull_interval_s)

            def publish_and_pump(params, _pub=publish):
                _pub(params)
                self._fanout.on_publish()
            publish = publish_and_pump
        self.learner.publish = publish
        # staleness clock (ISSUE 5): the learner half of sample-age =
        # publish count at flush − the block's generation stamp
        self.learner.weight_version_fn = lambda: self.store.publish_count
        self.queue = BlockQueue(use_mp=False)
        self._stop = stop
        self._actor_mode = "thread"
        if self.quality_stats is not None:
            # deployment plane (ISSUE 20): the promotion state machine
            # over THIS store/fan-out tree (its block rides the quality
            # record via stats.set_promotion), and the continuous-eval
            # client polling save_dir for new checkpoints — publish
            # stamps at eval time give the ledger its lineage.
            from r2d2_tpu.fleet.promotion import PromotionManager
            from r2d2_tpu.telemetry import QualityEvaluator
            self.promotion = PromotionManager(
                cfg.fleet, self.store, fanout=self._fanout,
                stats=self.quality_stats, save_dir=cfg.runtime.save_dir)
            self.quality_evaluator = QualityEvaluator(
                cfg, self.player_idx, self.quality_stats,
                interval_s=cfg.telemetry.quality_eval_interval_s,
                rounds=cfg.telemetry.quality_eval_rounds,
                clients=cfg.telemetry.quality_eval_clients,
                serve=(cfg.actor.inference == "server"),
                stamp_fn=lambda: self.store.publish_count).start()
        if self.serve_endpoint is not None:
            # thread-mode serving: the server polls the in-proc store
            # under its own reader id; clients share the stats object so
            # the serving block's latency is the CLIENT-visible round
            # trip (the SLO the chaos drill fires on)
            self._serve_weight_poll = lambda: self.store.poll("serve")
            self._serve_weight_version = \
                lambda: self.store.reader_version("serve")
            # fleet mode: each server slot is its OWN store reader
            # ("serve0", "serve1", ...) so the slots' weight adoption
            # and staleness stamps stay independent
            self._serve_weight_poll_factory = (
                lambda slot: (lambda: self.store.poll(f"serve{slot}")))
            self._serve_weight_version_factory = (
                lambda slot: (
                    lambda: self.store.reader_version(f"serve{slot}")))
            self._serve_copy_updates = True
            self._serve_client_timed = True
            self._start_serve_server()
        for i in range(cfg.actor.num_actors):
            self._spawn_thread_actor(i)
        while len(self.threads) < self.n_slots:
            self.threads.append(_VacantSlot())
        self._start_lease_server()

    def _spawn_thread_actor(self, i: int) -> threading.Thread:
        cfg = self.cfg
        seed = cfg.runtime.seed + 10_000 * self.player_idx + 100 * i
        # scalar (run_actor) or vectorized (run_vector_actor) per
        # cfg.actor.envs_per_actor — one shared construction path with the
        # spawned actor process and the throughput bench (actor_loop.py)
        # env_factory=create_env: route lane construction through THIS
        # module's symbol so tests can monkeypatch it
        env = make_actor_env(cfg, self.player_idx, i, seed,
                             env_factory=create_env,
                             num_players=cfg.multiplayer.num_players,
                             **self.actor_env_args(i))

        # per-spawn cancel event: the hang watchdog cannot kill a thread,
        # so it sets this and abandons the incarnation — a thread that
        # ever unwedges sees should_stop and exits instead of double-
        # feeding its slot
        cancel = threading.Event()

        def should_stop(cancel=cancel):
            return self._stop.is_set() or cancel.is_set()

        if self.serve_fleet is not None:
            # sharded serving: a routing channel over ALL fleet
            # endpoints — requests aim by client-id hash and re-aim on
            # MISROUTED bounces as the fleet grows/shrinks
            serve_channel = self.serve_fleet.connect()
            self._routing_channels.append(serve_channel)
            if self._shadow_mirror is not None:
                serve_channel.set_mirror(self._shadow_mirror)
        elif self.serve_endpoint is not None:
            serve_channel = self.serve_endpoint.connect()
        else:
            serve_channel = None
        # weight distribution endpoints for this slot: its leaf relay of
        # the fan-out tree when configured (ISSUE 15), the root store
        # directly otherwise — identical (poll, version, current) shapes
        if self._fanout is not None:
            fo_poll, fo_version, fo_current = self._fanout.endpoints(i)
        elif self.store is not None:
            fo_poll = (lambda reader_id=i: self.store.poll(reader_id))
            fo_version = (
                lambda reader_id=i: self.store.reader_version(reader_id))
            fo_current = (
                lambda reader_id=i: self.store.current(reader_id=reader_id))
        else:
            fo_poll = fo_version = fo_current = None
        # initial params: the distribution plane's CURRENT published
        # tree — already prepared (the quant bundle; no per-policy
        # requantization) AND fresh on a mid-training respawn/adoption,
        # whose dead predecessor consumed the slot's reader version so
        # its first poll() would return None; adopting here also fixes
        # the staleness stamp
        init_params = (fo_current() if fo_current is not None
                       else self.learner.train_state.params)
        policy, run_loop = make_actor_policy(
            cfg, self.net, init_params, i, seed,
            total_actors=self.n_slots,
            serve_channel=serve_channel, serve_stats=self.serve_stats,
            should_stop=should_stop, quant_stats=self.quant_stats)

        from r2d2_tpu.runtime.actor_loop import instrument_block_sink
        self.heartbeats.reset_slot(i)
        if serve_channel is not None:
            # served inference: the SERVER owns weight sync; the block's
            # staleness stamp is the publish count riding each reply
            weight_version = lambda: policy.weight_version  # noqa: E731
            weight_poll = lambda: None                      # noqa: E731
        else:
            # generation stamp: the version this slot's distribution
            # endpoint last adopted (relay-aware: a lagging relay's
            # consumers stamp OLDER versions, which is the truth)
            weight_version = fo_version
            weight_poll = fo_poll
        quality_feed = None
        if self.quality_stats is not None:
            # Q-calibration tap (ISSUE 20): the slot's LocalBuffers feed
            # predicted-vs-realized gaps, stamped with the version this
            # slot is acting with (the PR-5 lineage join)
            from r2d2_tpu.replay.structs import ReplaySpec
            from r2d2_tpu.telemetry import make_calibration_feed
            quality_feed = make_calibration_feed(
                self.quality_stats, gamma=cfg.optim.gamma,
                n_steps=ReplaySpec.from_config(cfg).forward,
                sample_every=cfg.telemetry.quality_calib_sample_every,
                stamp_fn=weight_version)
        sink = instrument_block_sink(
            cfg, i,
            lambda b: self.queue.put_patient(
                b, should_stop,
                beat=lambda: self.heartbeats.touch(i),
                telemetry=self.telemetry),
            board=self.heartbeats, telemetry=self.telemetry,
            weight_version=weight_version,
            # lane provenance (ISSUE 10): worker i owns the contiguous
            # global-ladder slice [i*k, (i+1)*k) — the same layout
            # vector_lane_epsilons spreads ε over, and the identity a
            # joiner adopts with the slot (ISSUE 15)
            lane_base=i * cfg.actor.envs_per_actor,
            # injected 'leave' faults park the slot for re-adoption
            # BEFORE the worker unwinds (tools/chaos.py ChaosLeave);
            # the generation gates leave injection to the slot's
            # ORIGINAL worker — an adopted incarnation is a new worker
            on_leave=lambda: self._on_worker_leave(i),
            generation=self.membership.generation(i))

        def loop(env=env, policy=policy, run_loop=run_loop,
                 weight_poll=weight_poll, sink=sink,
                 should_stop=should_stop, quality_feed=quality_feed):
            from r2d2_tpu.tools.chaos import ChaosLeave

            # the run loop owns env and closes it on every exit
            try:
                run_loop(cfg, env, policy,
                         block_sink=sink,
                         weight_poll=weight_poll,
                         should_stop=should_stop,
                         telemetry=self.telemetry,
                         quality_feed=quality_feed)
            except ChaosLeave:
                # deliberate departure (ISSUE 15): the slot already
                # parked via on_leave — unwind quietly, not as a crash
                pass
            except Exception:
                # a served policy raising ServeUnavailable DURING
                # shutdown is the clean-stop path, not a failure
                if not should_stop():
                    raise

        t = threading.Thread(target=loop, daemon=True,
                             name=f"actor-p{self.player_idx}-{i}")
        t.health_cancel = cancel
        t.start()
        if i < len(self.threads):
            self.threads[i] = t
        else:
            self.threads.append(t)
        return t

    def start_actors_processes(self, stop_event) -> None:
        cfg = self.cfg
        self._ctx = mp.get_context("spawn")
        prep = self._publish_prep
        params0 = self.learner.train_state.params
        self.publisher = WeightPublisher(
            prep(params0, 1) if prep else params0)
        publish = wrap_publish(
            self.publisher.publish, prep,
            lambda: self.publisher.publish_count)
        # shm fan-out tree (ISSUE 15): relay nodes re-publish the root
        # segment into their own segments; each actor process attaches
        # to its leaf relay's segment name through the unchanged
        # actor_main plumbing. Pumped on every publish + the supervise
        # cadence.
        if cfg.fleet.fanout_degree >= 2:
            from r2d2_tpu.fleet.fanout import ShmFanout
            template = prep(params0, 0) if prep else params0
            self._shm_fanout = ShmFanout(
                self.publisher.name, template, self.n_slots,
                cfg.fleet.fanout_degree)
            self._shm_fanout.pump()   # relays adopt the initial publish

            def publish_and_pump(params, _pub=publish):
                _pub(params)
                self._shm_fanout.pump()
            publish = publish_and_pump
        self.learner.publish = publish
        self.learner.weight_version_fn = \
            lambda: self.publisher.publish_count
        self.queue = BlockQueue(
            use_mp=True, ctx=self._ctx,
            shm_spec=self.learner.spec if cfg.runtime.shm_transport else None,
            tracing=(cfg.telemetry.enabled
                     and cfg.telemetry.tracing_enabled))
        self._stop = stop_event
        self._actor_mode = "process"
        if self.serve_endpoint is not None:
            self._start_serve_transport()
        for i in range(cfg.actor.num_actors):
            self._spawn_process_actor(i)
        while len(self.processes) < self.n_slots:
            self.processes.append(_VacantSlot())
        self._start_lease_server()

    def _start_serve_transport(self) -> None:
        """Process-mode serving: the server lives in THIS (learner)
        process and actor processes reach it over the transport ladder —
        the shm request/reply rings by default (the shm_feeder
        discipline), TCP loopback when forced or when the native
        toolchain is unavailable. The server reads weights through a
        WeightSubscriber on the existing publisher segment (one more
        reader, zero new mechanisms)."""
        cfg = self.cfg
        from r2d2_tpu.runtime.weights import WeightSubscriber
        # the subscriber template must match the PUBLISHED tree — the
        # inference bundle in quant mode (stamp value irrelevant: the
        # template only provides structure)
        template = self.learner.train_state.params
        if self._publish_prep is not None:
            template = self._publish_prep(template, 0)
        if cfg.serve.servers > 1:
            # sharded serving over processes (ISSUE 17): sockets only
            # (config validation rejects shm + servers>1 — the shm rings
            # are single-consumer). Each fleet slot reads weights through
            # its OWN WeightSubscriber (independent adoption cursors) and
            # listens on its own TCP port; the spec ships the full
            # address map + the initial shard assignment so actor
            # processes build a RoutingChannel without a handshake.
            subs = {}

            def _sub_for(slot):
                if slot not in subs:
                    s = WeightSubscriber(self.publisher.name, template)
                    subs[slot] = s
                    self._serve_weight_subs.append(s)
                return subs[slot]

            self._serve_weight_poll_factory = \
                lambda slot: _sub_for(slot).poll
            self._serve_weight_version_factory = (
                lambda slot: (lambda: _sub_for(slot).publish_count))
            self._serve_copy_updates = False
            self._serve_client_timed = False
            self._start_serve_server()     # builds the ServerFleet
            from r2d2_tpu.serve import SocketServerTransport
            servers = {}
            for slot, ep in self.serve_fleet.serve_spec_servers().items():
                port = cfg.serve.port + slot if cfg.serve.port else 0
                t = SocketServerTransport(ep.submit, cfg.serve.host, port)
                self._serve_fleet_transports.append(t)
                servers[slot] = (t.host, t.port)
            self._serve_spec = {
                "transport": "socket_fleet",
                "servers": servers,
                "total_shards": self.serve_fleet.total_shards,
                "assign": self.serve_fleet.shard_map.to_wire(),
            }
            return
        sub = WeightSubscriber(self.publisher.name, template)
        self._serve_weight_sub = sub
        self._serve_weight_poll = sub.poll
        self._serve_weight_version = lambda: sub.publish_count
        # WeightSubscriber.poll materializes a fresh copy per poll — the
        # server may own those buffers directly (actor_main's reasoning)
        self._serve_copy_updates = False
        # clients are in other processes: the server times request
        # latency itself (receive→reply; client timeouts still reach the
        # histogram through the chaos drill's in-proc path)
        self._serve_client_timed = False
        reply_slots = max(cfg.serve.reply_ring_slots,
                          cfg.actor.envs_per_actor)
        if cfg.serve.transport in ("auto", "shm"):
            try:
                from r2d2_tpu.serve import ShmServeTransport
                self._serve_transport = ShmServeTransport(
                    self.serve_endpoint.submit,
                    (cfg.env.frame_height, cfg.env.frame_width),
                    self.net.action_dim, cfg.network.hidden_dim,
                    request_slots=cfg.serve.request_ring_slots,
                    tracing=(cfg.telemetry.enabled
                             and cfg.telemetry.tracing_enabled))
                self._serve_spec = {
                    "transport": "shm",
                    "request_ring": self._serve_transport.request_ring,
                    "action_dim": self.net.action_dim,
                    "hidden_dim": cfg.network.hidden_dim,
                    "reply_slots": reply_slots,
                }
            except Exception as e:
                if cfg.serve.transport == "shm":
                    raise
                import logging
                logging.getLogger(__name__).warning(
                    "native shm serve transport unavailable (%s); "
                    "falling back to TCP loopback", e)
        if self._serve_spec is None:
            from r2d2_tpu.serve import SocketServerTransport
            self._serve_transport = SocketServerTransport(
                self.serve_endpoint.submit, cfg.serve.host, cfg.serve.port)
            self._serve_spec = {
                "transport": "socket",
                "host": self._serve_transport.host,
                "port": self._serve_transport.port,
            }
        self._start_serve_server()

    def _spawn_process_actor(self, i: int) -> mp.Process:
        cfg = self.cfg
        # the ε ladder spans the fleet's MAX width (n_slots == num_actors
        # unless fleet.max_slots reserves spares), so the exploration
        # schedule is fixed as the fleet churns
        eps = apex_epsilon(i, self.n_slots, cfg.actor.base_eps,
                           cfg.actor.eps_alpha)
        self.heartbeats.reset_slot(i)
        if self.tele_board is not None:
            # fresh incarnation: cumulative telemetry counts restart at
            # zero (the aggregator's reset detection handles the edge)
            self.tele_board.reset_slot(i)
        # weight segment: the slot's leaf relay under the shm fan-out
        # tree, the root publisher otherwise (identical subscriber API)
        shm_name = (self._shm_fanout.segment_for(i)
                    if self._shm_fanout is not None
                    else self.publisher.name)
        p = self._ctx.Process(
            target=actor_process_main,
            args=(cfg.to_dict(), self.player_idx, i, eps,
                  shm_name, self.queue._q, self._stop),
            kwargs={**self.actor_env_args(i),
                    "total_actors": self.n_slots,
                    "health_board": self.heartbeats, "health_slot": i,
                    "telemetry_board": self.tele_board,
                    "serve_spec": self._serve_spec,
                    "generation": self.membership.generation(i)},
            daemon=True, name=f"actor-p{self.player_idx}-{i}")
        p.start()
        if i < len(self.processes):
            self.processes[i] = p
        else:
            self.processes.append(p)
        return p

    def supervise(self) -> int:
        """One health pass: restart dead actors (the reference has no
        failure handling at all — a crashed Ray actor silently reduces
        throughput forever, SURVEY §5.3), kill+respawn HUNG ones (alive
        but heartbeat-stale), apply per-slot restart backoff and the
        crash-loop breaker, run the ingest stall detector, and surface the
        counters in TrainMetrics. Returns the number of restarts performed.

        Shm-ring slot reclamation runs for every NEWLY-failed actor
        process regardless of runtime.restart_dead_actors (round-3 advisor):
        a producer that died between reserve and commit wedges the ring head
        slot whether or not it gets respawned, and with restarts off the
        learner would otherwise starve even with other actors alive."""
        from r2d2_tpu.runtime.feeder import supervise_workers
        if self._stop.is_set():
            return 0
        if self.resources is not None:
            # resource sampling rides the supervision cadence (a cheap
            # time check; the sample itself is a handful of dict reads
            # per telemetry.resources_interval_s)
            self.resources.maybe_sample()
        if self.compile_monitor is not None and self.learner.training_steps:
            # warm-up ends when training has started: the train program
            # and the actor policies have compiled by now, so any further
            # compile of a known fn with new avals is a retrace (mark_warm
            # is idempotent — called every pass, latches once)
            self.compile_monitor.mark_warm()
        if self._shm_fanout is not None:
            # relay propagation rides the supervise cadence too, so a
            # publish between supervision passes still reaches leaves
            # promptly even if the publish-time pump raced a subscriber
            self._shm_fanout.pump()
        restart = self.cfg.runtime.restart_dead_actors
        # elastic membership (ISSUE 15): a dead/left worker's slot PARKS
        # for re-adoption instead of respawning in place — joiners
        # (join_actor / the grammar's join@t schedule) re-admit it
        park = self._park_slot if self.cfg.fleet.elastic else None
        restarted = 0
        if self.serve_fleet is not None:
            # serving-fleet health rides the same cadence (ISSUE 17): a
            # dead server's slot parks, survivors adopt its orphaned
            # cache shards, and clients re-route off MISROUTED bounces
            restarted += self.serve_fleet.supervise()
        # threads are scanned even with restarts off (respawn=None), like
        # processes below: the hang watchdog must still flag a wedged
        # thread and feed the failure counters — restart_dead_actors
        # gates RESPAWNING, not detection
        restarted += supervise_workers(
            self.threads, self._seen_dead,
            respawn=(self._spawn_thread_actor
                     if restart and park is None else None),
            health=self.health, park=park)
        restarted += supervise_workers(
            self.processes, self._seen_dead,
            respawn=(self._spawn_process_actor
                     if restart and park is None else None),
            ring=self._ring_recovery,
            health=self.health, park=park)
        self.health.ring_slots_recovered += self._ring_recovery.tick(
            self.queue)
        # grammar-scheduled joins (join@t=S): admit once the slot is
        # parked/free and the schedule time elapsed
        if self._join_schedule:
            from r2d2_tpu.fleet.membership import SLOT_ACTIVE
            now_rel = time.time() - self._run_start
            for slot, fault in self._join_schedule.items():
                if slot in self._joins_done or now_rel < fault.t:
                    continue
                if self.membership.state(slot) == SLOT_ACTIVE:
                    continue       # still occupied; retry next pass
                self.join_actor(slot)
                self._joins_done.add(slot)
                restarted += 1
        workers = self.processes or self.threads
        self._stall.check(
            self.metrics.ingest_blocks_total,
            sum(1 for w in workers if w.is_alive()),
            self.learner.ingestion_paused,
            diagnostics=self._stall_diagnostics)
        self.metrics.set_actor_health(
            {**self.health.snapshot(),
             "ingest_stall_dumps": self._stall.dumps})
        return restarted

    # -- elastic membership (ISSUE 15) --

    def _on_worker_leave(self, slot: int) -> None:
        """The sink's on_leave hook (an injected ``leave`` fault): park
        the slot BEFORE the worker unwinds, so the supervisor sees a
        detached slot, never a crash."""
        self.membership.park(slot, reason="left")
        self.health.detach(slot)

    def _park_slot(self, slot: int, hung: bool) -> None:
        """Elastic supervision policy: a dead (or watchdog-killed hung)
        worker's slot parks for re-adoption — no in-place respawn, no
        backoff ladder; training continues on the remaining fleet."""
        import logging
        self.membership.park(slot, reason="hung" if hung else "died")
        self.health.detach(slot)
        logging.getLogger(__name__).warning(
            "elastic fleet: worker slot %d %s — slot PARKED for "
            "re-adoption (active fleet now %d/%d)", slot,
            "hung" if hung else "died",
            len(self.membership.active_slots()), self.n_slots)

    def leave_actor(self, slot: int) -> None:
        """Deliberate departure: park the slot's lease and stop its
        worker. The slot's lane range / ε slice / replay routing are
        preserved for the next joiner; the learner keeps training on
        the remaining fleet."""
        from r2d2_tpu.runtime.feeder import kill_worker
        self.membership.park(slot, reason="left")
        self.health.detach(slot)
        workers = self.processes if self.processes else self.threads
        if slot < len(workers):
            w = workers[slot]
            if not isinstance(w, _VacantSlot):
                kill_worker(w)
                self._seen_dead.add(w)

    def join_actor(self, slot: Optional[int] = None):
        """Admit a joiner into a RUNNING fleet: lease a parked (or
        spare) slot and spawn a worker that adopts its full identity —
        heartbeat row, lane range, ε-ladder slice, replay routing. The
        new worker reads weights through the slot's distribution
        endpoint (leaf relay under fan-out) and its blocks carry the
        adopted lane stamps, so provenance checks span the churn."""
        lease = self.membership.lease(slot)
        i = lease.slot
        self.health.attach(i)
        corpse = None
        workers = self.processes if self._actor_mode == "process" \
            else self.threads
        if i < len(workers):
            corpse = workers[i]
        if self._actor_mode == "process":
            self._spawn_process_actor(i)
        else:
            self._spawn_thread_actor(i)
        if corpse is not None:
            self._seen_dead.discard(corpse)
        return lease

    def _start_lease_server(self) -> None:
        """Socket face of the lease table (ROADMAP 2c; gated on
        ``fleet.lease_transport == "socket"``): ``cli/join.py`` dials
        this to admit an acting worker into the running fleet — the SAME
        ``join_actor`` slot-adoption path the in-process join schedule
        uses — or to grow/shrink the serving fleet (ISSUE 17)."""
        if self.cfg.fleet.lease_transport != "socket":
            return
        from r2d2_tpu.fleet.membership import MembershipServer

        def _join(slot=None):
            lease = self.join_actor(slot)
            return {"slot": lease.slot, "generation": lease.generation,
                    "lane_base": lease.lane_base, "lanes": lease.lanes,
                    "shard_key": lease.shard_key}

        def _leave(slot):
            self.leave_actor(int(slot))
            return {"slot": int(slot)}

        def _grow_serve():
            return {"slot": self.grow_serve_server(),
                    "servers": sorted(self.serve_fleet.servers)}

        def _shrink_serve(slot=None):
            return {"slot": self.shrink_serve_server(slot),
                    "servers": sorted(self.serve_fleet.servers)}

        def _announce_replay(host, port, shards=None, step=None,
                             anchor_wall=None):
            # ISSUE 18: a (re)started ReplayService re-registers its
            # address after restoring from snapshot — producers that
            # lost their socket rediscover the survivor via 'info'.
            # ISSUE 19: the announcement is also the clock-anchor
            # exchange — the board echoes ITS wall clock at receipt, so
            # the announcer can estimate its skew against the learner
            # plane (offset ≈ anchor_wall - board_wall, good to ±RTT/2)
            # without any shared monotonic clock.
            self._replay_announce = {"host": str(host), "port": int(port),
                                     "shards": shards, "step": step,
                                     "t": time.time()}
            if anchor_wall is not None:
                self._replay_announce["anchor_wall"] = float(anchor_wall)
            return {"ok": True, "board_wall": time.time()}

        def _info():
            info = {"membership": self.membership.snapshot(),
                    "actor_mode": self._actor_mode}
            if self._replay_announce is not None:
                info["replay_service"] = self._replay_announce
            if self.promotion is not None:
                # cli/promote.py --status dials this
                info["promotion"] = self.promotion.block()
            if self.serve_fleet is not None:
                info["serving"] = {
                    "servers": sorted(self.serve_fleet.servers),
                    "map_version": self.serve_fleet.shard_map.version,
                }
            if (self._serve_spec is not None
                    and self._serve_spec.get("transport") != "shm"):
                # socket specs travel (a joiner can dial the servers);
                # the shm spec's ring handle is same-host/spawn-only
                info["serve_spec"] = self._serve_spec
            return info

        self._lease_server = MembershipServer(
            {"join": _join, "leave": _leave, "grow_serve": _grow_serve,
             "shrink_serve": _shrink_serve, "info": _info,
             "announce_replay": _announce_replay},
            host=self.cfg.fleet.lease_host,
            port=self.cfg.fleet.lease_port)
        import logging
        logging.getLogger(__name__).info(
            "fleet lease API on %s:%d", self._lease_server.host,
            self._lease_server.port)

    def grow_serve_server(self) -> int:
        """Elastic serving fleet (ISSUE 17): lease a parked/free server
        slot, re-slice the shard map, and hand the boundary shard groups
        to the new server. Returns the grown slot."""
        if self.serve_fleet is None:
            raise RuntimeError("grow_serve_server requires serve.servers"
                               " > 1 (a running ServerFleet)")
        return self.serve_fleet.grow_server()

    def shrink_serve_server(self, slot: Optional[int] = None) -> int:
        """Retire a serving-fleet server: its shard groups rehome to the
        survivors (leases, op-dedup and hidden state ride along), then
        the slot parks. Returns the retired slot."""
        if self.serve_fleet is None:
            raise RuntimeError("shrink_serve_server requires serve.servers"
                               " > 1 (a running ServerFleet)")
        return self.serve_fleet.shrink_server(slot)

    def _replay_service_block(self):
        """The record's ``replay_service`` block: shard/spill health
        from the learner's service, fan-out relay stats, membership
        lease counts (orphan horizon = 2x the hang timeout — a leased
        slot silent that long has no supervision verdict coming)."""
        block = {}
        if self.learner.service is not None:
            block.update(self.learner.service.interval_block())
        if self._service_server is not None:
            # windowed socket rung (ISSUE 16): per-interval frame/block
            # counts, max in-flight window occupancy, injected ack drops
            block["socket"] = self._service_server.interval_stats()
        if self._fanout is not None:
            block["fanout"] = self._fanout.stats()
        elif self._shm_fanout is not None:
            block["fanout"] = self._shm_fanout.stats(
                self.publisher.publish_count)
        horizon = 2.0 * self.cfg.runtime.hang_timeout_s
        block["membership"] = self.membership.snapshot(
            self.heartbeats.ages() if self.heartbeats is not None else None,
            orphan_horizon_s=horizon)
        return block

    def _stall_diagnostics(self) -> dict:
        """Snapshot for the one-shot stall dump: who was alive, how stale
        each heartbeat was, and where the pipeline stood."""
        lr = self.learner
        workers = self.processes or self.threads
        return {
            "heartbeat_ages_s": [round(float(a), 1)
                                 for a in self.heartbeats.ages()],
            "heartbeat_counts": [int(c) for c in self.heartbeats.counts()],
            "workers_alive": [w.is_alive() for w in workers],
            "parked_slots": [i for i in range(self.cfg.actor.num_actors)
                             if self.health.is_parked(i)],
            "queue_depth": self.queue.qsize() if self.queue else -1,
            "buffer_steps": lr.ring.buffer_steps,
            "staged_blocks": lr._staged_blocks,
            "ingestion_paused": lr.ingestion_paused,
            "training_steps": lr.training_steps,
        }

    def close(self) -> None:
        self.learner.stop_background()
        if self.quality_evaluator is not None:
            self.quality_evaluator.stop()
        self.clear_shadow()
        if self._lease_server is not None:
            self._lease_server.close()
        if self._service_server is not None:
            self._service_server.close()
        if self.serve_server is not None:
            self.serve_server.stop()
        if self.serve_fleet is not None:
            self.serve_fleet.stop()
        if self._serve_transport is not None:
            self._serve_transport.close()
        for t in self._serve_fleet_transports:
            t.close()
        if self._serve_weight_sub is not None:
            self._serve_weight_sub.close()
        for s in self._serve_weight_subs:
            s.close()
        if self._shm_fanout is not None:
            # relays close BEFORE the root publisher: each holds a
            # subscriber on the root (or a parent relay's) segment
            self._shm_fanout.close()
        if self.publisher is not None:
            self.publisher.close()
        for p in self.processes:
            if isinstance(p, _VacantSlot):
                continue           # spare membership slot, never spawned
            p.join(timeout=5.0)
            if p.is_alive():
                p.terminate()
                p.join(timeout=2.0)
            if p.is_alive():
                # terminate ignored (wedged engine child): escalate so a
                # zombie never outlives the run
                p.kill()
                p.join(timeout=2.0)
        # join thread actors too: a daemon actor thread still inside an XLA
        # compile when the interpreter exits dies with a C++ abort
        # ("FATAL: exception not rethrown") — harmless but alarming noise
        for t in self.threads:
            if isinstance(t, _VacantSlot):
                continue
            t.join(timeout=5.0)
        if self.queue is not None:
            self.queue.close()   # releases/unlinks the shm ring (owner)
        self.heartbeats.close()  # releases/unlinks the heartbeat board
        self.telemetry.close()   # stops the drain thread, final flush
        if self.tele_board is not None:
            self.tele_board.close()
        if self.compile_monitor is not None:
            # restore the pxla logger exactly (level/propagation) and
            # release the process-global active-monitor slot
            self.compile_monitor.uninstall()


def train(cfg: Config, *, max_training_steps: Optional[int] = None,
          max_seconds: Optional[float] = None, actor_mode: str = "thread",
          log_fn: Callable[[dict], None] = None) -> List[PlayerStack]:
    """Run the full system; returns the player stacks (learners hold final
    state). Blocking — the reference's train.py never returns either
    (train.py:60-66); here max_training_steps / max_seconds bound the run."""
    assert actor_mode in ("thread", "process")
    if cfg.actor.on_device:
        # Anakin-style fully on-device acting (ISSUE 6): the fused
        # act+train loop replaces the whole actor fleet — no threads, no
        # processes, no block queue, no weight service (actor_mode is
        # moot). Everything below this guard is the legacy path,
        # byte-identical when the knob is off.
        from r2d2_tpu.runtime.anakin_loop import run_anakin_train
        return run_anakin_train(cfg, max_training_steps=max_training_steps,
                                max_seconds=max_seconds, log_fn=log_fn)
    if cfg.mesh.multihost:
        # DCN bring-up BEFORE any backend use, so jax.devices() sees the
        # whole slice (SURVEY §5.8; validated by the two-process loopback
        # dryrun in parallel/multihost_dryrun.py). Every host runs this
        # same train() as an SPMD controller. This single-controller loop
        # dispatches at its own cadence, which multi-controller JAX cannot
        # tolerate — multi-process jobs must use the rank-aware lockstep
        # loop instead (parallel/multihost.py; cli/train.py routes there
        # automatically).
        if cfg.mesh.num_processes > 1:
            raise NotImplementedError(
                "mesh.multihost training with num_processes > 1 must go "
                "through r2d2_tpu.parallel.multihost.train_multihost (the "
                "lockstep multi-controller loop; cli/train.py routes there "
                "automatically) — this single-controller train() would "
                "dispatch collective programs at diverging per-host "
                "cadences.")
        from r2d2_tpu.parallel import init_distributed
        init_distributed(cfg.mesh)
    num_players = cfg.multiplayer.num_players if cfg.multiplayer.enabled else 1

    # probe env for the action dim (ref worker.py:259 creates a throwaway env)
    probe = create_env(cfg.env, seed=cfg.runtime.seed)
    action_dim = probe.action_space.n
    probe.close()

    if actor_mode == "thread":
        stop = threading.Event()
    else:
        stop = mp.get_context("spawn").Event()

    # Map external SIGTERM/SIGINT onto the clean stop path: a hard kill of a
    # process holding a live TPU dispatch can wedge a remote-TPU tunnel for
    # every process that follows (observed round 1 — it cost both driver
    # artifacts). Only the main thread may install handlers; restored below.
    prev_handlers = {}
    stacks: List[PlayerStack] = []
    # profiler capture triggers (telemetry/profiler.CaptureTriggers —
    # ONE shared implementation with the fused anakin loop, ISSUE 9):
    # legacy first-interval (profile_dir set), runtime.profile_at_step
    # (one-shot, fires when the learner step counter first reaches it),
    # and SIGUSR2 (on demand, any number of times). Start/stop are
    # idempotent, so the finally below can always uninstall without
    # tracking which trigger started a capture.
    from r2d2_tpu.telemetry.profiler import CaptureTriggers
    triggers = CaptureTriggers(cfg.runtime)
    try:
        # Everything after handler installation sits inside this try so the
        # finally always restores them — even when stack construction or
        # actor startup raises.
        if threading.current_thread() is threading.main_thread():
            def _on_signal(signum, frame):
                if stop.is_set():
                    # Second signal: the clean path is already requested but
                    # may be blocked inside a wedged device call — restore
                    # the previous handler so a repeated Ctrl+C/SIGTERM can
                    # still interrupt rather than being swallowed forever.
                    prev = prev_handlers.get(signum) or signal.SIG_DFL
                    signal.signal(signum, prev)
                    if signum == signal.SIGINT:
                        raise KeyboardInterrupt
                    return
                stop.set()
            for sig in (signal.SIGTERM, signal.SIGINT):
                try:
                    prev_handlers[sig] = signal.signal(sig, _on_signal)
                except (ValueError, OSError):
                    pass

        # SIGUSR2 flag handler (main-thread check inside; restore in
        # triggers.uninstall — the handler only flags, the loop starts
        # the capture outside signal context)
        triggers.install()

        # player_id >= 0: this job runs exactly ONE player of the
        # population (per-player-job composition — README "Multiplayer at
        # pod scale"); the player index still feeds the host/join wiring
        # and seed offsets, so N such jobs reproduce the in-process
        # population stack-for-stack.
        if cfg.multiplayer.enabled and cfg.multiplayer.player_id >= 0:
            player_indices = [cfg.multiplayer.player_id]
        else:
            player_indices = list(range(num_players))
        # appended one-by-one (not a comprehension): PlayerStack.__init__
        # allocates the heartbeat shm segment, and the finally below only
        # closes stacks that made it into the list — a mid-population
        # construction failure must not leak the earlier stacks' segments
        for p in player_indices:
            stacks.append(PlayerStack(cfg, p, action_dim))
        for st in stacks:
            if actor_mode == "thread":
                st.start_actors_threads(stop)
            else:
                st.start_actors_processes(stop)

        start = time.time()
        deadline = start + max_seconds if max_seconds else None
        max_steps = max_training_steps or cfg.optim.training_steps
        last_log = last_supervise = start

        def timed_out() -> bool:
            return deadline is not None and time.time() > deadline

        def supervise_due() -> bool:
            # supervision runs on its own cadence, decoupled from the log
            # interval, in BOTH loops — an actor that dies or hangs before
            # learning_starts used to go unsupervised and wedge warm-up
            # until the deadline
            nonlocal last_supervise
            if time.time() - last_supervise < cfg.runtime.supervise_interval_s:
                return False
            last_supervise = time.time()
            return True

        # warm-up: fill buffers to learning_starts (ref train.py:49-54).
        # drain() bursts at replay.drain_max_blocks here AND in the
        # training loop below — one knob, no silently different warm-up
        # rate — and routes to the pipelined stager when
        # replay.ingest_batch_blocks > 1.
        while (not all(st.learner.ready for st in stacks) and not timed_out()
               and not stop.is_set()):
            for st in stacks:
                st.learner.drain(st.queue)
            if supervise_due():
                for st in stacks:
                    st.supervise()
            time.sleep(0.02)

        # initial step-0 checkpoint (ref worker.py:311)
        for st in stacks:
            if cfg.runtime.save_interval:
                st.learner.save(0)

        # optional jax.profiler trace of the first training interval
        # (SURVEY §5.1 — the reference has no profiling at all); capture
        # lifecycle owned by ProfilerCapture so an exception anywhere can
        # neither leave a trace running nor stop a dead one
        triggers.start_first_interval()

        while (not timed_out() and not stop.is_set()
               and any(st.learner.training_steps < max_steps for st in stacks)):
            for st in stacks:
                st.learner.drain(st.queue)
                if st.learner.ready and st.learner.training_steps < max_steps:
                    st.learner.step()
            now = time.time()
            # mid-run capture triggers: end an elapsed window, fire the
            # one-shot profile_at_step when ANY player's step counter
            # first crosses it, service a pending SIGUSR2 request
            triggers.poll(now, max(
                (st.learner.training_steps for st in stacks), default=0))
            if supervise_due():
                for st in stacks:
                    st.supervise()
            if now - last_log >= cfg.runtime.log_interval:
                for st in stacks:
                    st.learner.flush_metrics()
                    record = st.metrics.log(now - last_log)
                    if log_fn:
                        log_fn({"player": st.player_idx, **record})
                last_log = now
        for st in stacks:
            st.learner.flush_metrics()
    finally:
        triggers.uninstall()  # stop any live capture, restore SIGUSR2
        stop.set()
        for st in stacks:
            # preemption-safe final checkpoint: a clean stop (SIGTERM/
            # SIGINT or deadline) between periodic saves would otherwise
            # resume from the last interval boundary, replaying work
            try:
                if cfg.runtime.save_interval:
                    st.learner.save_final()
            except Exception:
                import logging
                logging.getLogger(__name__).exception(
                    "final checkpoint for player %d failed", st.player_idx)
            st.close()
        for sig, handler in prev_handlers.items():
            try:
                signal.signal(sig, handler)
            except (ValueError, OSError):
                pass
    return stacks
