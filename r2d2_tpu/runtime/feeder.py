"""Experience transport: actor processes → learner host thread.

Replaces the reference's ``replay_buffer.add.remote(block)`` through Ray's
object store (/root/reference/worker.py:558,565). A bounded multiprocessing
queue of fixed-shape Block records; the learner drains it between fused train
steps and ingests via the jitted ``replay_add``. Bounded so a stalled learner
back-pressures actors instead of exhausting host RAM.
"""

import json
import logging
import multiprocessing as mp
import queue as queue_mod
import subprocess
import time
from collections import deque
from multiprocessing import shared_memory
from typing import Callable, List, Optional

import numpy as np

from r2d2_tpu.replay.structs import Block


def put_patient(q, block: Block, should_stop, poll: float = 0.5,
                beat: Optional[Callable[[], None]] = None,
                telemetry=None) -> bool:
    """Blocking put that survives indefinite back-pressure (the rate
    limiter deliberately parks actors here) but still honors the stop
    signal. Returns False iff stopped before the block was accepted.
    Module-level because process-mode actors receive the raw (picklable)
    mp.Queue, not the BlockQueue wrapper — one implementation serves both
    (actor_main imports this; BlockQueue.put_patient delegates).
    ``beat`` (the worker's HeartbeatBoard.touch) is called once per poll
    iteration so a deliberately parked producer keeps reading as ALIVE to
    the hang watchdog — back-pressure is not a hang. ``telemetry``
    observes the whole entry-to-accepted wait as 'actor/queue_put' — the
    stage whose tail IS the back-pressure signal."""
    t0 = time.perf_counter()
    while not should_stop():
        if beat is not None:
            beat()
        try:
            q.put(block, timeout=poll)
            if telemetry is not None:
                telemetry.observe("actor/queue_put",
                                  time.perf_counter() - t0)
            return True
        except queue_mod.Full:
            continue
    return False


class HeartbeatBoard:
    """Per-slot worker liveness: an (n_slots, 2) float64 table
    [progress_count, last_beat_unix_ts] in ONE ``multiprocessing.
    shared_memory`` region, so thread and process workers publish through
    the identical object. Publishing (``beat``: one row store per block
    emit; ``touch``: timestamp only, from parked ``put_patient`` polls) is
    off the policy hot path. Picklable like ShmBlockRing: the handle
    crosses the spawn boundary by name and the child attaches lazily; the
    creating process owns the region and unlinks it on close()."""

    def __init__(self, n_slots: int, _attach_name: Optional[str] = None):
        self.n_slots = n_slots
        self._owner = _attach_name is None
        self._shm = None
        self._arr = None
        self._final = None        # post-close snapshot for post-mortem reads
        if self._owner:
            self._shm = shared_memory.SharedMemory(
                create=True, size=n_slots * 2 * 8)
            self._bind()
            self._arr[:, 0] = 0.0
            self._arr[:, 1] = time.time()
        else:
            self._name = _attach_name

    def __getstate__(self):
        return {"n_slots": self.n_slots, "name": self.name}

    def __setstate__(self, state):
        self.__init__(state["n_slots"], _attach_name=state["name"])

    @property
    def name(self) -> str:
        return self._shm.name if self._shm is not None else self._name

    def _bind(self) -> None:
        self._arr = np.ndarray((self.n_slots, 2), np.float64, self._shm.buf)

    def _ensure(self) -> np.ndarray:
        if self._shm is None:
            if self._final is not None:
                # closed: serve the frozen snapshot (chaos reports and
                # tests read counters after the run tears down)
                return self._final
            from r2d2_tpu.runtime.weights import untrack_attached_shm
            self._shm = shared_memory.SharedMemory(name=self._name)
            untrack_attached_shm(self._shm)
            self._bind()
        return self._arr

    def beat(self, slot: int) -> None:
        """Progress heartbeat: one row store per block emit."""
        arr = self._ensure()
        arr[slot] = (arr[slot, 0] + 1.0, time.time())

    def touch(self, slot: int) -> None:
        """Liveness without progress (parked producer)."""
        self._ensure()[slot, 1] = time.time()

    def reset_slot(self, slot: int) -> None:
        """Fresh incarnation: called at every (re)spawn so the new worker
        starts its own grace clock."""
        self._ensure()[slot] = (0.0, time.time())

    def count(self, slot: int) -> int:
        return int(self._ensure()[slot, 0])

    def counts(self) -> np.ndarray:
        return self._ensure()[:, 0].copy()

    def age(self, slot: int, now: Optional[float] = None) -> float:
        now = time.time() if now is None else now
        return max(0.0, now - float(self._ensure()[slot, 1]))

    def ages(self, now: Optional[float] = None) -> np.ndarray:
        now = time.time() if now is None else now
        return np.maximum(now - self._ensure()[:, 1], 0.0)

    def close(self) -> None:
        if self._shm is None:
            return
        self._final = self._arr.copy()
        self._arr = None
        self._shm.close()
        if self._owner:
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass
        self._shm = None


class WorkerHealth:
    """Per-slot worker health policy: hang detection over a HeartbeatBoard,
    exponential restart backoff, and a crash-loop circuit breaker — ONE
    implementation shared by the single-host supervisor
    (orchestrator.PlayerStack) and the multihost fleet
    (parallel/multihost.LocalActorFleet), driven by ``supervise_workers``.

    Backoff: the first failure of a slot respawns immediately; each
    further failure inside ``restart_window_s`` doubles the wait, starting
    at ``backoff_base_s`` for the second (k-th failure waits
    ``backoff_base_s * 2^(k-2)``, capped at ``backoff_max_s``). Breaker:
    after ``max_restarts_per_window``
    failures inside the window the slot is PARKED — no further respawns,
    training continues degraded, and the trip is surfaced loudly (warning
    log + actor_parked_slots / actor_breaker_trips in TrainMetrics)."""

    def __init__(self, n_slots: int, board: Optional[HeartbeatBoard] = None,
                 hang_timeout_s: float = 0.0,
                 hang_spawn_grace_s: float = 300.0,
                 backoff_base_s: float = 1.0, backoff_max_s: float = 60.0,
                 max_restarts_per_window: int = 0,
                 restart_window_s: float = 300.0):
        self.board = board
        self.hang_timeout_s = hang_timeout_s
        self.hang_spawn_grace_s = hang_spawn_grace_s
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.max_restarts_per_window = max_restarts_per_window
        self.restart_window_s = restart_window_s
        self._windows = [deque() for _ in range(n_slots)]  # failure times
        self._next_allowed = [0.0] * n_slots
        self._parked = [False] * n_slots
        # membership-detached slots (ISSUE 15): a slot whose lease is
        # parked/vacant — the supervisor must neither hang-check nor
        # respawn it (distinct from the breaker's _parked: detachment is
        # a deliberate membership state, not a failure verdict, and it
        # re-attaches on join)
        self._detached = [False] * n_slots
        self.restarts = 0
        self.hangs_detected = 0
        self.breaker_trips = 0
        self.ring_slots_recovered = 0

    @classmethod
    def from_runtime(cls, n_slots: int, board: Optional[HeartbeatBoard],
                     rt) -> "WorkerHealth":
        """Build from a RuntimeConfig (duck-typed: any object carrying the
        runtime.* health fields)."""
        return cls(n_slots, board,
                   hang_timeout_s=rt.hang_timeout_s,
                   hang_spawn_grace_s=rt.hang_spawn_grace_s,
                   backoff_base_s=rt.restart_backoff_base_s,
                   backoff_max_s=rt.restart_backoff_max_s,
                   max_restarts_per_window=rt.max_restarts_per_window,
                   restart_window_s=rt.restart_window_s)

    def check_hung(self, slot: int, now: float) -> bool:
        """True when the slot's heartbeat has gone stale: hang_timeout_s
        after any beat, hang_spawn_grace_s (if longer) before the
        incarnation's FIRST beat (spawn + env construction can dwarf the
        steady-state block cadence)."""
        if self.board is None or self.hang_timeout_s <= 0:
            return False
        timeout = self.hang_timeout_s
        if self.board.count(slot) == 0:
            timeout = max(timeout, self.hang_spawn_grace_s)
        return self.board.age(slot, now) > timeout

    def on_failure(self, slot: int, now: float, hung: bool = False) -> None:
        """Record one failure (death or hang) for the slot: advances the
        backoff ladder and may trip the breaker."""
        log = logging.getLogger(__name__)
        if hung:
            self.hangs_detected += 1
            log.warning(
                "worker slot %d HUNG (alive, heartbeat %.1fs stale): "
                "killing and routing through respawn", slot,
                self.board.age(slot, now) if self.board is not None else -1.0)
        win = self._windows[slot]
        cutoff = now - self.restart_window_s
        while win and win[0] < cutoff:
            win.popleft()
        prior = len(win)
        win.append(now)
        if (self.max_restarts_per_window > 0
                and prior + 1 > self.max_restarts_per_window):
            self._parked[slot] = True
            self.breaker_trips += 1
            log.warning(
                "circuit breaker TRIPPED: worker slot %d failed %d times "
                "within %.0fs — slot parked, training continues degraded",
                slot, prior + 1, self.restart_window_s)
            return
        delay = 0.0 if prior == 0 else min(
            self.backoff_base_s * 2.0 ** (prior - 1), self.backoff_max_s)
        self._next_allowed[slot] = now + delay
        if delay:
            log.warning(
                "worker slot %d failed %d time(s) in the last %.0fs: "
                "respawn backed off %.1fs", slot, prior + 1,
                self.restart_window_s, delay)

    def is_parked(self, slot: int) -> bool:
        return self._parked[slot]

    def detach(self, slot: int) -> None:
        """Membership detachment (ISSUE 15): the slot's lease parked (a
        worker left/died under the elastic policy) or the slot is spare
        capacity awaiting a joiner — supervision skips it entirely."""
        self._detached[slot] = True

    def attach(self, slot: int) -> None:
        """Re-admission: a joiner adopted the slot. The failure window
        and backoff reset — the new incarnation is a fresh lease, not a
        continuation of the departed worker's crash history."""
        self._detached[slot] = False
        self._windows[slot].clear()
        self._next_allowed[slot] = 0.0

    def is_detached(self, slot: int) -> bool:
        return self._detached[slot]

    def respawn_due(self, slot: int, now: float) -> bool:
        return not self._parked[slot] and now >= self._next_allowed[slot]

    def on_spawn(self, slot: int) -> None:
        self.restarts += 1

    def snapshot(self, now: Optional[float] = None) -> dict:
        """Supervision counters for the periodic TrainMetrics record."""
        age_max = None
        if self.board is not None:
            ages = self.board.ages(now)
            if len(ages):
                age_max = round(float(ages.max()), 1)
        return {
            "actor_restarts": self.restarts,
            "actor_hangs_detected": self.hangs_detected,
            "actor_breaker_trips": self.breaker_trips,
            "actor_parked_slots": int(sum(self._parked)),
            "shm_slots_recovered": self.ring_slots_recovered,
            "heartbeat_age_max_s": age_max,
        }


def kill_worker(w) -> None:
    """Forcibly clear a hung worker. Process: terminate → short join →
    kill escalation (a wedged ViZDoom child can ignore SIGTERM). Thread:
    python cannot kill a thread — set its per-spawn cancel event (the
    spawner's should_stop honors it if the thread ever unwedges) and
    abandon it; the replacement takes its slot."""
    cancel = getattr(w, "health_cancel", None)
    if cancel is not None:
        cancel.set()
    if hasattr(w, "terminate"):
        w.terminate()
        w.join(timeout=1.0)
        if w.is_alive() and hasattr(w, "kill"):
            w.kill()
            w.join(timeout=1.0)


class IngestStallDetector:
    """Learner-side stall detector: fires ONCE per stall episode when
    ingestion sits at zero new blocks for ``timeout_s`` while workers are
    nominally alive and the rate limiter is not deliberately pausing —
    emitting a diagnostic dump (heartbeat ages, queue/ring occupancy,
    limiter state) instead of starving silently. Re-arms when blocks flow
    again."""

    def __init__(self, timeout_s: float):
        self.timeout_s = timeout_s
        self._last_total: Optional[int] = None
        self._last_change: Optional[float] = None
        self._fired = False
        self._was_paused = False
        self.dumps = 0

    def check(self, blocks_total: int, workers_alive: int,
              limiter_paused: bool, now: Optional[float] = None,
              diagnostics: Optional[Callable[[], dict]] = None) -> bool:
        if self.timeout_s <= 0:
            return False
        now = time.time() if now is None else now
        if self._last_total is None or blocks_total != self._last_total:
            self._last_total = blocks_total
            self._last_change = now
            self._fired = False
            return False
        if limiter_paused:
            # a deliberate rate-limiter pause is not a stall; the clock
            # restarts at the first unpaused observation
            self._was_paused = True
            self._last_change = now
            return False
        if self._was_paused:
            self._was_paused = False
            self._last_change = now
            return False
        if (self._fired or workers_alive == 0
                or now - self._last_change < self.timeout_s):
            return False
        self._fired = True
        self.dumps += 1
        info = diagnostics() if diagnostics is not None else {}
        logging.getLogger(__name__).warning(
            "ingestion STALLED: zero blocks for %.1fs with %d worker(s) "
            "nominally up — diagnostics: %s",
            now - self._last_change, workers_alive,
            json.dumps(info, default=str))
        return True


class RingRecoveryScheduler:
    """Schedules ``BlockQueue.recover_stalled`` after actor-process deaths.

    A producer that died between reserve and commit wedges an shm ring
    slot. Reclamation must run AFTER the slot-grace window (an immediate
    attempt finds the slot not yet stale — recover_stalled's 5s grace
    protects live writers) but must not be deferred by further deaths
    (a crash-looping actor would push it forever), and must re-arm when a
    death lands inside a pass's grace window. ONE implementation shared by
    the single-host supervisor (orchestrator.PlayerStack) and the
    multihost fleet (parallel/multihost.LocalActorFleet)."""

    def __init__(self, grace: float = 6.0):
        self._grace = grace
        self._after: Optional[float] = None
        self._last_death = 0.0

    def on_death(self) -> None:
        import time
        self._last_death = time.time()
        if self._after is None:
            self._after = self._last_death + self._grace

    def tick(self, queue) -> int:
        """Run a due reclamation pass against ``queue``; returns slots
        freed (0 when none due)."""
        import time
        if self._after is None or time.time() < self._after:
            return 0
        freed = queue.recover_stalled()
        # re-arm when a death landed inside this pass's grace window — its
        # wedged slot was not yet stale for the pass that just ran
        self._after = (self._last_death + self._grace
                       if self._last_death + self._grace > time.time()
                       else None)
        if freed:
            import logging
            logging.getLogger(__name__).warning(
                "recovered %d shm ring slot(s) wedged by crashed actor(s)",
                freed)
        return freed


def supervise_workers(workers, seen_dead: set, respawn=None,
                      ring: Optional[RingRecoveryScheduler] = None,
                      health: Optional[WorkerHealth] = None,
                      park: Optional[Callable[[int, bool], None]] = None
                      ) -> int:
    """The ONE worker-health scan shared by the single-host supervisor
    (orchestrator.PlayerStack) and the multihost fleet
    (parallel/multihost.LocalActorFleet).

    ``workers`` is a list of threads or processes (anything with
    ``is_alive``). A worker counts as FAILED when it is dead, or — with
    ``health`` — alive but hung (stale heartbeat; it is killed/flagged via
    ``kill_worker``). Each newly-failed worker notifies ``ring`` when given
    (shm slot reclamation) and feeds ``health`` (backoff ladder, breaker).
    With ``respawn``, a failed worker is replaced by ``respawn(i)`` once
    its backoff elapses and its slot is not parked — ``respawn`` may
    return None to keep the corpse and retry next tick. ``seen_dead``
    (holding the objects — no id reuse) makes every corpse count exactly
    once, so a slot waiting out its backoff cannot re-arm ring reclamation
    or re-advance the backoff ladder every tick. Returns the number
    respawned.

    ``park`` (ISSUE 15, fleet.elastic): the membership policy — a
    newly-failed worker's slot is PARKED (``park(i, hung)``) instead of
    fed to the backoff ladder and respawned in place; ring reclamation
    still runs (a crashed producer wedges shm slots either way), and
    slots the membership plane detached are skipped like breaker-parked
    ones."""
    restarted = 0
    now = time.time()
    for i, w in enumerate(workers):
        if health is not None and (health.is_parked(i)
                                   or health.is_detached(i)):
            continue
        known_corpse = w in seen_dead
        if not known_corpse:
            if w.is_alive():
                if health is None or not health.check_hung(i, now):
                    continue
                hung = True        # alive but wedged: clear it now
                kill_worker(w)
            else:
                hung = False
            seen_dead.add(w)
            if ring is not None:
                ring.on_death()
            if park is not None:
                # elastic membership: the slot parks for re-adoption —
                # no backoff, no in-place respawn; a joiner re-attaches
                park(i, hung)
                continue
            if health is not None:
                health.on_failure(i, now, hung=hung)
        if respawn is None:
            continue
        if health is not None and not health.respawn_due(i, now):
            continue
        new = respawn(i)
        if new is not None:
            workers[i] = new
            # the corpse left the list: drop it so seen_dead stays bounded
            # by the fleet size over a days-long run, not by total failures
            seen_dead.discard(w)
            if health is not None:
                health.on_spawn(i)
            restarted += 1
    return restarted


class BlockQueue:
    """Works in all modes: the native shm ring (shm_feeder.py) or mp.Queue
    for process actors, queue.Queue for thread actors (hermetic tests).

    ``shm_spec``: pass the ReplaySpec to use the native shared-memory
    transport (one memcpy per side instead of pickling through a pipe); if
    the native toolchain is unavailable the queue degrades to mp.Queue with
    a warning. close() releases/unlinks the shm region (owner side)."""

    def __init__(self, maxsize: int = 64, use_mp: bool = True,
                 ctx: Optional[mp.context.BaseContext] = None,
                 shm_spec=None, tracing: bool = False):
        if use_mp and shm_spec is not None:
            try:
                from r2d2_tpu.runtime.shm_feeder import ShmBlockRing
                self._q = ShmBlockRing(shm_spec, maxsize, tracing=tracing)
                return
            except (ImportError, OSError, subprocess.CalledProcessError) as e:
                import logging
                logging.getLogger(__name__).warning(
                    "native shm transport unavailable (%s); falling back "
                    "to mp.Queue", e)
        if use_mp:
            ctx = ctx or mp.get_context("spawn")
            self._q = ctx.Queue(maxsize=maxsize)
        else:
            self._q = queue_mod.Queue(maxsize=maxsize)

    def put(self, block: Block, timeout: Optional[float] = None) -> None:
        self._q.put(block, timeout=timeout)

    def put_patient(self, block: Block, should_stop, poll: float = 0.5,
                    beat: Optional[Callable[[], None]] = None,
                    telemetry=None) -> bool:
        return put_patient(self._q, block, should_stop, poll, beat=beat,
                           telemetry=telemetry)

    def drain(self, max_items: int = 16) -> List[Block]:
        """Non-blocking drain of up to max_items blocks."""
        out = []
        for _ in range(max_items):
            try:
                out.append(self._q.get_nowait())
            except queue_mod.Empty:
                break
        return out

    def drain_stacked(self, max_items: int = 16):
        """Non-blocking drain of up to max_items blocks as ONE stacked Block
        (leading K axis on every leaf) — the batched-ingestion transport
        contract. On the native shm ring the fields stream straight from the
        ring slots into contiguous stacked arrays (zero intermediate
        copies); other queue backends fall back to get_nowait + np.stack.
        Returns (stacked_block, k); (None, 0) when the queue is empty."""
        fn = getattr(self._q, "drain_stacked", None)
        if fn is not None:
            return fn(max_items)
        blocks = self.drain(max_items)
        if not blocks:
            return None, 0
        import jax
        stacked = jax.tree_util.tree_map(
            lambda *xs: np.stack([np.asarray(x) for x in xs]), *blocks)
        return stacked, len(blocks)

    def drain_groups(self, group: int, max_groups: int = 4):
        """Non-blocking drain as a LIST of stacked groups, each of up to
        ``group`` blocks: [(stacked_block, k), ...] in arrival order.
        This is the producer-pump shape (fleet.ReplayProducerPump): a
        deep backlog becomes several window-sized frames in one pass
        instead of one oversized frame, so the socket rung's pipelining
        (fleet.socket_window) has frames to overlap. Returns [] when the
        queue is empty."""
        groups = []
        for _ in range(max(int(max_groups), 1)):
            stacked, k = self.drain_stacked(group)
            if k == 0:
                break
            groups.append((stacked, k))
        return groups

    def qsize(self) -> int:
        """Best-effort queue depth; -1 when the backend cannot say (the
        ingest stager then drains without accumulation/bucketing)."""
        try:
            return int(self._q.qsize())
        except (NotImplementedError, OSError):
            return -1

    def get(self, timeout: Optional[float] = None) -> Block:
        return self._q.get(timeout=timeout)

    def close(self) -> None:
        closer = getattr(self._q, "close", None)
        if closer is not None:
            closer()

    def recover_stalled(self) -> int:
        """Free ring slots wedged by a crashed producer (shm transport
        only; no-op otherwise). The supervisor calls this after reaping a
        dead actor process."""
        fn = getattr(self._q, "recover_stalled", None)
        return fn() if fn is not None else 0
