"""Experience transport: actor processes → learner host thread.

Replaces the reference's ``replay_buffer.add.remote(block)`` through Ray's
object store (/root/reference/worker.py:558,565). A bounded multiprocessing
queue of fixed-shape Block records; the learner drains it between fused train
steps and ingests via the jitted ``replay_add``. Bounded so a stalled learner
back-pressures actors instead of exhausting host RAM.
"""

import multiprocessing as mp
import queue as queue_mod
import subprocess
from typing import List, Optional

import numpy as np

from r2d2_tpu.replay.structs import Block


def put_patient(q, block: Block, should_stop, poll: float = 0.5) -> bool:
    """Blocking put that survives indefinite back-pressure (the rate
    limiter deliberately parks actors here) but still honors the stop
    signal. Returns False iff stopped before the block was accepted.
    Module-level because process-mode actors receive the raw (picklable)
    mp.Queue, not the BlockQueue wrapper — one implementation serves both
    (actor_main imports this; BlockQueue.put_patient delegates)."""
    while not should_stop():
        try:
            q.put(block, timeout=poll)
            return True
        except queue_mod.Full:
            continue
    return False


class RingRecoveryScheduler:
    """Schedules ``BlockQueue.recover_stalled`` after actor-process deaths.

    A producer that died between reserve and commit wedges an shm ring
    slot. Reclamation must run AFTER the slot-grace window (an immediate
    attempt finds the slot not yet stale — recover_stalled's 5s grace
    protects live writers) but must not be deferred by further deaths
    (a crash-looping actor would push it forever), and must re-arm when a
    death lands inside a pass's grace window. ONE implementation shared by
    the single-host supervisor (orchestrator.PlayerStack) and the
    multihost fleet (parallel/multihost.LocalActorFleet)."""

    def __init__(self, grace: float = 6.0):
        self._grace = grace
        self._after: Optional[float] = None
        self._last_death = 0.0

    def on_death(self) -> None:
        import time
        self._last_death = time.time()
        if self._after is None:
            self._after = self._last_death + self._grace

    def tick(self, queue) -> int:
        """Run a due reclamation pass against ``queue``; returns slots
        freed (0 when none due)."""
        import time
        if self._after is None or time.time() < self._after:
            return 0
        freed = queue.recover_stalled()
        # re-arm when a death landed inside this pass's grace window — its
        # wedged slot was not yet stale for the pass that just ran
        self._after = (self._last_death + self._grace
                       if self._last_death + self._grace > time.time()
                       else None)
        if freed:
            import logging
            logging.getLogger(__name__).warning(
                "recovered %d shm ring slot(s) wedged by crashed actor(s)",
                freed)
        return freed


def supervise_workers(workers, seen_dead: set, respawn=None,
                      ring: Optional[RingRecoveryScheduler] = None) -> int:
    """The ONE dead-worker scan shared by the single-host supervisor
    (orchestrator.PlayerStack) and the multihost fleet
    (parallel/multihost.LocalActorFleet).

    ``workers`` is a list of threads or processes (anything with
    ``is_alive``). Each newly-dead worker notifies ``ring`` when given
    (shm slot reclamation). With ``respawn``, each dead worker is replaced
    by ``respawn(i)`` — return None to keep the dead one and retry next
    tick. Without ``respawn``, ``seen_dead`` (holding the objects — no id
    reuse) counts a permanently-dead worker exactly once, so it cannot
    re-schedule reclamation every tick. Returns the number respawned."""
    restarted = 0
    for i, w in enumerate(workers):
        if w.is_alive():
            continue
        if respawn is not None:
            if ring is not None:
                ring.on_death()
            new = respawn(i)
            if new is not None:
                workers[i] = new
                restarted += 1
        elif w not in seen_dead:
            seen_dead.add(w)
            if ring is not None:
                ring.on_death()
    return restarted


class BlockQueue:
    """Works in all modes: the native shm ring (shm_feeder.py) or mp.Queue
    for process actors, queue.Queue for thread actors (hermetic tests).

    ``shm_spec``: pass the ReplaySpec to use the native shared-memory
    transport (one memcpy per side instead of pickling through a pipe); if
    the native toolchain is unavailable the queue degrades to mp.Queue with
    a warning. close() releases/unlinks the shm region (owner side)."""

    def __init__(self, maxsize: int = 64, use_mp: bool = True,
                 ctx: Optional[mp.context.BaseContext] = None,
                 shm_spec=None):
        if use_mp and shm_spec is not None:
            try:
                from r2d2_tpu.runtime.shm_feeder import ShmBlockRing
                self._q = ShmBlockRing(shm_spec, maxsize)
                return
            except (ImportError, OSError, subprocess.CalledProcessError) as e:
                import logging
                logging.getLogger(__name__).warning(
                    "native shm transport unavailable (%s); falling back "
                    "to mp.Queue", e)
        if use_mp:
            ctx = ctx or mp.get_context("spawn")
            self._q = ctx.Queue(maxsize=maxsize)
        else:
            self._q = queue_mod.Queue(maxsize=maxsize)

    def put(self, block: Block, timeout: Optional[float] = None) -> None:
        self._q.put(block, timeout=timeout)

    def put_patient(self, block: Block, should_stop, poll: float = 0.5) -> bool:
        return put_patient(self._q, block, should_stop, poll)

    def drain(self, max_items: int = 16) -> List[Block]:
        """Non-blocking drain of up to max_items blocks."""
        out = []
        for _ in range(max_items):
            try:
                out.append(self._q.get_nowait())
            except queue_mod.Empty:
                break
        return out

    def drain_stacked(self, max_items: int = 16):
        """Non-blocking drain of up to max_items blocks as ONE stacked Block
        (leading K axis on every leaf) — the batched-ingestion transport
        contract. On the native shm ring the fields stream straight from the
        ring slots into contiguous stacked arrays (zero intermediate
        copies); other queue backends fall back to get_nowait + np.stack.
        Returns (stacked_block, k); (None, 0) when the queue is empty."""
        fn = getattr(self._q, "drain_stacked", None)
        if fn is not None:
            return fn(max_items)
        blocks = self.drain(max_items)
        if not blocks:
            return None, 0
        import jax
        stacked = jax.tree_util.tree_map(
            lambda *xs: np.stack([np.asarray(x) for x in xs]), *blocks)
        return stacked, len(blocks)

    def qsize(self) -> int:
        """Best-effort queue depth; -1 when the backend cannot say (the
        ingest stager then drains without accumulation/bucketing)."""
        try:
            return int(self._q.qsize())
        except (NotImplementedError, OSError):
            return -1

    def get(self, timeout: Optional[float] = None) -> Block:
        return self._q.get(timeout=timeout)

    def close(self) -> None:
        closer = getattr(self._q, "close", None)
        if closer is not None:
            closer()

    def recover_stalled(self) -> int:
        """Free ring slots wedged by a crashed producer (shm transport
        only; no-op otherwise). The supervisor calls this after reaping a
        dead actor process."""
        fn = getattr(self._q, "recover_stalled", None)
        return fn() if fn is not None else 0
