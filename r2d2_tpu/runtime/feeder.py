"""Experience transport: actor processes → learner host thread.

Replaces the reference's ``replay_buffer.add.remote(block)`` through Ray's
object store (/root/reference/worker.py:558,565). A bounded multiprocessing
queue of fixed-shape Block records; the learner drains it between fused train
steps and ingests via the jitted ``replay_add``. Bounded so a stalled learner
back-pressures actors instead of exhausting host RAM.
"""

import multiprocessing as mp
import queue as queue_mod
from typing import List, Optional

from r2d2_tpu.replay.structs import Block


def put_patient(q, block: Block, should_stop, poll: float = 0.5) -> bool:
    """Blocking put that survives indefinite back-pressure (the rate
    limiter deliberately parks actors here) but still honors the stop
    signal. Returns False iff stopped before the block was accepted.
    Module-level because process-mode actors receive the raw (picklable)
    mp.Queue, not the BlockQueue wrapper — one implementation serves both
    (actor_main imports this; BlockQueue.put_patient delegates)."""
    while not should_stop():
        try:
            q.put(block, timeout=poll)
            return True
        except queue_mod.Full:
            continue
    return False


class BlockQueue:
    """Works in both modes: mp.Queue for process actors, queue.Queue for
    thread actors (hermetic tests)."""

    def __init__(self, maxsize: int = 64, use_mp: bool = True,
                 ctx: Optional[mp.context.BaseContext] = None):
        if use_mp:
            ctx = ctx or mp.get_context("spawn")
            self._q = ctx.Queue(maxsize=maxsize)
        else:
            self._q = queue_mod.Queue(maxsize=maxsize)

    def put(self, block: Block, timeout: Optional[float] = None) -> None:
        self._q.put(block, timeout=timeout)

    def put_patient(self, block: Block, should_stop, poll: float = 0.5) -> bool:
        return put_patient(self._q, block, should_stop, poll)

    def drain(self, max_items: int = 16) -> List[Block]:
        """Non-blocking drain of up to max_items blocks."""
        out = []
        for _ in range(max_items):
            try:
                out.append(self._q.get_nowait())
            except queue_mod.Empty:
                break
        return out

    def get(self, timeout: Optional[float] = None) -> Block:
        return self._q.get(timeout=timeout)
