"""Training metrics and logging, key-compatible with the reference.

The reference centralizes counters in the ReplayBuffer actor and writes
``train_player{p}.log`` lines that plot.py regex-matches
(/root/reference/worker.py:35-37,220-234; plot.py:33-48). This class keeps the
exact key strings so the reference's offline plots work unchanged, and adds a
structured JSONL stream for programmatic consumers.
"""

import json
import logging
import os
import time
from typing import Optional

import numpy as np


class TrainMetrics:
    def __init__(self, player_idx: int = 0, log_dir: str = ".",
                 jsonl: bool = True, resume: bool = False):
        self.player_idx = player_idx
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
        self.logger = logging.getLogger(f"r2d2_tpu.player_{player_idx}")
        self.logger.setLevel(logging.INFO)
        self.logger.propagate = False
        path = os.path.join(log_dir or ".", f"train_player{player_idx}.log")
        # resume=True (runtime.resume set): APPEND — a preempted run
        # resuming from its final checkpoint must not wipe the log/JSONL
        # history the plots and the inspector are built from; a fresh run
        # truncates both (the JSONL is opened "a" per record, so it needs
        # the explicit truncation here).
        handler = logging.FileHandler(path, "a" if resume else "w")
        handler.setFormatter(logging.Formatter("%(message)s"))
        self.logger.handlers = [handler]
        self._jsonl_path = (os.path.join(log_dir or ".", f"metrics_player{player_idx}.jsonl")
                            if jsonl else None)
        if self._jsonl_path and not resume:
            open(self._jsonl_path, "w").close()
        self._start = time.time()
        # telemetry aggregator (set_telemetry): owns the stage timers this
        # record's 'stages' block summarizes. NULL keeps learner-only
        # constructions working with zero branching at the call sites.
        from r2d2_tpu.telemetry import NULL_TELEMETRY
        self.telemetry = NULL_TELEMETRY

        self.buffer_size = 0
        self.env_steps = 0
        self.last_env_steps = 0
        self.num_episodes = 0
        self.episode_reward = 0.0
        self.training_steps = 0
        self.last_training_steps = 0
        self.sum_loss = 0.0
        self.dropped_priority_updates = 0
        self._next_drop_warn = 1

        # ingestion observability (ISSUE 2): per-interval accumulators,
        # reset at each log(), plus a cumulative block counter the e2e
        # bench reads for whole-run blocks/s. Locked: the pipelined
        # stager thread feeds on_ingest_pause while the main thread's
        # log() resets — an unguarded read-modify-write would double-count
        # or drop an interval's pause time.
        import threading
        self._ingest_lock = threading.Lock()
        self.ingest_blocks_total = 0
        self._ingest_drains = 0
        self._ingest_blocks = 0
        self._ingest_latency_sum = 0.0
        self._ingest_pause_time = 0.0
        self.ingest_queue_depth = 0

        # worker-health counters (ISSUE 3): last supervision snapshot
        # (PlayerStack.supervise / the multihost fleet push WorkerHealth's
        # cumulative counters here); defaults keep the record schema
        # stable for learner-only runs that never supervise
        self._actor_health = {}

        # learning-dynamics block (ISSUE 5): set per flush by the
        # LearningAggregator; emitted once per record then cleared, and
        # OMITTED entirely when learning diagnostics are off (consumers
        # key on its presence, like the 'stages' block)
        self._learning = None

        # sharded-anakin composition block (ISSUE 8): per-shard rows +
        # the env-step imbalance ratio, set at each stats flush by the
        # fused loop; emitted once per record then cleared, OMITTED on
        # every non-anakin run (consumers key on its presence)
        self._anakin = None

        # fleet observability block (ISSUE 12): set per flush by the
        # rank-0 FleetAggregator (per-rank step-time table, straggler
        # rank, lockstep-wait fraction, env-step divergence, host-row
        # ages, merged fleet stage histograms); emitted once per record
        # then cleared, OMITTED on every non-multihost run and under the
        # telemetry.fleet_enabled kill switch (schema byte-identical to
        # PR10, stability-tested)
        self._fleet = None

        # replay & data-pathology block (ISSUE 10): set per flush by the
        # ReplayDiagAggregator (sum-tree health, eviction lifetimes, lane
        # composition); emitted once per record then cleared, OMITTED
        # entirely under the telemetry.replay_diag_enabled kill switch
        # (schema byte-identical to PR9, stability-tested)
        self._replay_diag = None

        # cost-model block (ISSUE 9): the analytic per-component
        # flops/bytes summary of the configured step, set ONCE by the
        # Learner's first flush and emitted on the next record only (it
        # is static per config — re-emitting every interval would bloat
        # the JSONL with constants); OMITTED entirely under the
        # telemetry.costmodel_enabled kill switch (schema byte-identical
        # to pre-PR9, stability-tested)
        self._costs = None

        # serving plane (ISSUE 13): a serving-block provider
        # (ServingStats.interval_block, attached by the orchestrating
        # loop when actor.inference="server" or a standalone server
        # shares this metrics stream) — called once per log(); a None
        # return (no serving traffic this interval) omits the key, and
        # an unattached provider (every local-inference run) leaves the
        # record byte-identical to the pre-PR13 schema.
        self._serving_fn = None

        # quantized inference plane (ISSUE 14): a quant-block provider
        # (QuantStats.interval_block, attached by the orchestrating loop
        # when network.inference_dtype != "f32") — called once per
        # log(); unattached (every f32 run) the record is byte-identical
        # to the PR13 schema.
        self._quant_fn = None

        # policy-quality pillar (ISSUE 20): a quality-block provider
        # (QualityLedger.interval_block — Q-calibration join, continuous
        # per-scenario eval with checkpoint lineage, shadow divergence,
        # promotion state; the provider also appends the
        # quality_player{p}.jsonl ledger row) — called once per log();
        # unattached (telemetry.quality_enabled off, the default) the
        # record is byte-identical to the PR19 schema.
        self._quality_fn = None

        # elastic fleet plane (ISSUE 15): a replay_service-block
        # provider (per-shard fill, spill occupancy/hit-rate, fan-out
        # relay depth/lag, membership lease counts) attached by the
        # orchestrating loop when any fleet plane is configured on —
        # unattached (every legacy run) the record is byte-identical to
        # the PR14 schema.
        self._replay_service_fn = None

        # crash-recovery plane (ISSUE 18): a recovery-block provider
        # (Learner.recovery_block — snapshot age/bytes/durations, restore
        # counts, estimated lost blocks, supervisor restarts) attached by
        # the orchestrating loop when runtime.snapshot_interval > 0 —
        # unattached (every run with the plane off) the record is
        # byte-identical to the PR17 schema.
        self._recovery_fn = None

        # cross-plane tracing (ISSUE 19): a trace-block provider
        # (ExperienceTrace.interval_block — the end-to-end env-step ->
        # gradient latency histogram with its per-hop breakdown)
        # attached by the learner when telemetry.tracing_enabled —
        # unattached (the kill switch, every legacy run) the record is
        # byte-identical to the PR18 schema.
        self._tracing_fn = None

        # system-health pillar (ISSUE 7): a resources-block provider
        # (ResourceMonitor.block) and the alert engine, both attached by
        # the orchestrating loop. None = the blocks are OMITTED and the
        # record schema is byte-identical to pre-PR7 (the
        # telemetry.resources_enabled kill switch; stability-tested).
        self._resources_fn = None
        self._sentinel = None

    # -- feed points --

    def on_block(self, learning_steps: int, episode_return: Optional[float]) -> None:
        """Called per ingested block (ref worker.py:117-120)."""
        self.env_steps += learning_steps
        if episode_return is not None and not np.isnan(episode_return):
            self.episode_reward += float(episode_return)
            self.num_episodes += 1

    def on_episodes(self, count: int, return_sum: float) -> None:
        """Batched episode-return feed for the fused on-device acting path:
        episode ends are counted on device and fetched as per-interval
        (count, sum) aggregates, so the return average matches on_block's
        per-episode feed without a host transfer per episode."""
        if count > 0 and np.isfinite(return_sum):
            self.episode_reward += float(return_sum)
            self.num_episodes += int(count)

    def on_train_step(self, loss: float) -> None:
        """Called per learner step (ref worker.py:211-212)."""
        self.training_steps += 1
        self.sum_loss += float(loss)

    def set_buffer_size(self, size: int) -> None:
        self.buffer_size = int(size)

    def on_ingest_drain(self, blocks: int, latency: float) -> None:
        """Called once per non-empty ingestion drain: ``blocks`` blocks
        entered the replay in one batch, ``latency`` seconds from queue pop
        to replay commit (the pipelined path's stage→commit lag; the
        legacy path's synchronous drain+ingest wall time)."""
        with self._ingest_lock:
            self._ingest_drains += 1
            self._ingest_blocks += blocks
            self.ingest_blocks_total += blocks
            self._ingest_latency_sum += latency

    def on_ingest_pause(self, seconds: float) -> None:
        """Rate-limiter pause time: ingestion stood still for ``seconds``
        while collection was ahead of the collect:learn budget."""
        with self._ingest_lock:
            self._ingest_pause_time += seconds

    def set_ingest_queue_depth(self, depth: int) -> None:
        """Staged batches awaiting commit (pipelined ingestion gauge)."""
        self.ingest_queue_depth = int(depth)

    def set_telemetry(self, telemetry) -> None:
        """Attach the process's Telemetry: log() then emits the aggregated
        per-interval 'stages' block (P50/P95/P99 per pipeline stage,
        fleet-wide when an actor TelemetryBoard is attached to it)."""
        self.telemetry = telemetry

    def set_learning(self, block: Optional[dict]) -> None:
        """Attach the interval's learning-diagnostics block (|TD|/priority
        /Q histograms, grad norms, ΔQ, staleness — telemetry/learning.py);
        None = nothing this interval (no training steps, or diagnostics
        disabled) and the record carries no 'learning' key."""
        self._learning = block

    def set_anakin(self, block: Optional[dict]) -> None:
        """Attach the interval's sharded-anakin block (per-shard env
        steps / episodes / return sums + the max/min env-step imbalance
        ratio — runtime/anakin_loop.py flush_stats); None = nothing this
        interval and the record carries no 'anakin' key."""
        self._anakin = block

    def set_fleet(self, block: Optional[dict]) -> None:
        """Attach the interval's fleet-observability block (per-rank
        step-time skew, straggler identity, lockstep-wait fraction,
        env-step divergence, host-row ages — telemetry/fleet.py); None =
        nothing this interval and the record carries no 'fleet' key."""
        self._fleet = block

    def set_replay_diag(self, block: Optional[dict]) -> None:
        """Attach the interval's replay-diagnostics block (sum-tree
        health + collapse indicators, per-slot eviction lifetimes with
        the never-sampled fraction, ε-lane composition of the sampled
        batches — telemetry/replaydiag.py); None = nothing this interval
        (no training, or the pillar disabled) and the record carries no
        'replay_diag' key."""
        self._replay_diag = block

    def set_costs(self, block: Optional[dict]) -> None:
        """Attach the one-shot cost-model block (ISSUE 9): analytic
        per-component flops/bytes + the serial-chain model for the
        configured step (telemetry/costmodel.analytic_component_costs).
        Emitted on exactly one record then cleared; None = no block."""
        self._costs = block

    def set_serving(self, provider) -> None:
        """Attach the serving-block provider (ISSUE 13): a callable
        returning ``ServingStats.interval_block()`` — request/reply
        counts, latency percentiles, batch-fill histogram summary,
        client lease churn. Called once per log(); None returns omit
        the block (consumers key on its presence)."""
        self._serving_fn = provider

    def set_quant(self, provider) -> None:
        """Attach the quant-block provider (ISSUE 14): a callable
        returning ``QuantStats.interval_block()`` — the active inference
        dtype, probe count, max |Q_f32 − Q_quant|, and greedy-action
        agreement of the interval's in-graph accuracy probes. Called
        once per log(); None returns omit the block."""
        self._quant_fn = provider

    def set_quality(self, provider) -> None:
        """Attach the quality-block provider (ISSUE 20): a callable
        returning ``QualityLedger.interval_block()`` — the interval's
        Q-calibration gap stats, the latest per-scenario eval rows with
        checkpoint lineage, shadow-scoring divergence, and the promotion
        state machine's sub-block. Called once per log(); None returns
        omit the block (consumers key on its presence)."""
        self._quality_fn = provider

    def set_replay_service(self, provider) -> None:
        """Attach the replay_service-block provider (ISSUE 15): a
        callable returning the elastic-fleet telemetry dict — per-shard
        fill/adds, spill-tier occupancy + hit-rate + interval thrash,
        fan-out relay depth/lag, membership lease counts. ISSUE 16 adds
        key-gated sub-blocks the provider emits only when their feature
        is on (record-schema byte-identity at defaults): "ingest"
        (grouped-dispatch counters + backlog — the ingest_backlog alert
        rule reads replay_service.ingest.backlog from here), "socket"
        (windowed-frame server stats), and spill prefetch/write-back
        counters inside "spill". Called once per log(); None returns
        omit the block (consumers key on its presence)."""
        self._replay_service_fn = provider

    def set_recovery(self, provider) -> None:
        """Attach the recovery-block provider (ISSUE 18): a callable
        returning the crash-recovery telemetry dict — latest replay
        snapshot (age/bytes/capture+write durations/step), restore
        counts + restored blocks, the estimated at-risk block count
        (adds since the last snapshot), supervisor restart count.
        Called once per log(); None returns omit the block (consumers
        key on its presence)."""
        self._recovery_fn = provider

    def set_tracing(self, provider) -> None:
        """Attach the trace-block provider (ISSUE 19): a callable
        returning ``ExperienceTrace.interval_block()`` — the sampled
        row count, the e2e_experience_latency histogram summary
        (env-step emission -> gradient consumption), and its per-hop
        breakdown (emit_to_ingest / ingest_to_sample / sample_to_train).
        Called once per log(); None returns omit the block (consumers
        key on its presence)."""
        self._tracing_fn = provider

    def set_resources(self, provider) -> None:
        """Attach the resources-block provider (ISSUE 7): a callable
        returning the ResourceMonitor's ``block()`` dict — called once
        per log() so EVERY periodic record carries a ``resources``
        entry while the pillar is enabled."""
        self._resources_fn = provider

    def set_sentinel(self, engine) -> None:
        """Attach the alert engine (ISSUE 7): log() evaluates the rule
        set against the assembled record — alerts see the same interval
        they alert on — and the record carries the resulting ``alerts``
        block; firings append to alerts_player{p}.jsonl inside the
        engine."""
        self._sentinel = engine

    def set_actor_health(self, snapshot: dict) -> None:
        """Supervision counters (WorkerHealth.snapshot + stall-dump count)
        for the periodic record — restarts, hangs, breaker trips, parked
        slots, heartbeat staleness."""
        self._actor_health = dict(snapshot)

    def on_dropped_priority_update(self) -> None:
        """Called when a priority write-back batch is dropped because the
        async write-back queue is saturated (host placement). Dropping
        silently degrades PER toward uniform sampling, so make it loud:
        warn at the first drop and at each 10x milestone after (the stdlib
        lastResort handler shows WARNING+ even with logging unconfigured)."""
        self.dropped_priority_updates += 1
        if self.dropped_priority_updates >= self._next_drop_warn:
            logging.getLogger(__name__).warning(
                "player %d: %d priority write-back batch(es) dropped under "
                "write-back queue backpressure — PER is degrading toward "
                "uniform sampling; the write-back thread is not keeping up",
                self.player_idx, self.dropped_priority_updates)
            self._next_drop_warn *= 10

    # -- emission (exact reference key strings, ref worker.py:220-234) --

    def log(self, log_interval: float) -> dict:
        self.logger.info(f"buffer size: {self.buffer_size}")
        buffer_speed = (self.env_steps - self.last_env_steps) / log_interval
        self.logger.info(f"buffer update speed: {buffer_speed}/s")
        self.logger.info(f"number of environment steps: {self.env_steps}")
        avg_return = None
        if self.num_episodes != 0:
            avg_return = self.episode_reward / self.num_episodes
            self.logger.info(f"average episode return: {avg_return:.4f}")
            self.episode_reward = 0.0
            self.num_episodes = 0
        self.logger.info(f"number of training steps: {self.training_steps}")
        train_speed = (self.training_steps - self.last_training_steps) / log_interval
        self.logger.info(f"training speed: {train_speed}/s")
        mean_loss = None
        if self.training_steps != self.last_training_steps:
            mean_loss = self.sum_loss / (self.training_steps - self.last_training_steps)
            self.logger.info(f"loss: {mean_loss:.4f}")
            self.last_training_steps = self.training_steps
            self.sum_loss = 0.0
        self.last_env_steps = self.env_steps

        record = {
            "t": time.time() - self._start,
            "buffer_size": self.buffer_size,
            "buffer_speed": buffer_speed,
            "env_steps": self.env_steps,
            "avg_episode_return": avg_return,
            "training_steps": self.training_steps,
            "training_speed": train_speed,
            "loss": mean_loss,
            "dropped_priority_updates": self.dropped_priority_updates,
            # worker-health counters: cumulative, overlaid by the latest
            # supervision snapshot when a supervisor is running
            "actor_restarts": 0,
            "actor_hangs_detected": 0,
            "actor_breaker_trips": 0,
            "actor_parked_slots": 0,
            "shm_slots_recovered": 0,
            "ingest_stall_dumps": 0,
            "heartbeat_age_max_s": None,
        }
        record.update(self._actor_health)
        with self._ingest_lock:
            # ingestion observability (per-interval; the e2e bench's
            # ingestion phase reads these)
            record.update({
                "ingest_blocks_total": self.ingest_blocks_total,
                "ingest_drains": self._ingest_drains,
                "ingest_blocks_per_drain": (
                    round(self._ingest_blocks / self._ingest_drains, 2)
                    if self._ingest_drains else None),
                "ingest_drain_latency_ms": (
                    round(1e3 * self._ingest_latency_sum
                          / self._ingest_drains, 3)
                    if self._ingest_drains else None),
                "ingest_queue_depth": self.ingest_queue_depth,
                "ingest_pause_time": round(self._ingest_pause_time, 3),
            })
            self._ingest_drains = 0
            self._ingest_blocks = 0
            self._ingest_latency_sum = 0.0
            self._ingest_pause_time = 0.0
        if self._learning is not None:
            # ONE learning block per interval (ISSUE 5) — consumed on
            # emission so a training pause doesn't replay stale numbers
            record["learning"] = self._learning
            self._learning = None
        if self._anakin is not None:
            # ONE anakin block per interval (ISSUE 8), consumed like the
            # learning block; emitted before the sentinel pass so the
            # shard_imbalance rule sees its own interval
            record["anakin"] = self._anakin
            self._anakin = None
        if self._fleet is not None:
            # ONE fleet block per interval (ISSUE 12), consumed on
            # emission; before the sentinel pass so the rank_straggler /
            # lockstep_wait_frac / fleet_desync / missing_rank rules see
            # their own interval
            record["fleet"] = self._fleet
            self._fleet = None
        if self._replay_diag is not None:
            # ONE replay_diag block per interval (ISSUE 10), consumed on
            # emission; before the sentinel pass so the priority-collapse
            # / never-sampled / lane-starvation rules see their own
            # interval
            record["replay_diag"] = self._replay_diag
            self._replay_diag = None
        if self._costs is not None:
            # ONE costs block per run (ISSUE 9), consumed on emission —
            # the numbers are pure config constants, so one record
            # carries them and the stream stays lean
            record["costs"] = self._costs
            self._costs = None
        if self.telemetry.enabled:
            # ONE aggregated block per interval covering the whole fleet:
            # learner-local stage timers merged with the actor board's
            # per-slot deltas (ISSUE 4). Omitted entirely when telemetry
            # is off — consumers key on its presence, and the PR-2/3 keys
            # above are unaffected either way (schema-stability-tested).
            record["stages"] = self.telemetry.interval_summary()
            record["telemetry_dropped_spans"] = self.telemetry.spans.dropped
        if self._serving_fn is not None:
            # serving block (ISSUE 13): request latency / batch fill /
            # client churn for the interval. Before the sentinel pass so
            # the serve_* rules see their own interval; a no-traffic
            # interval returns None and the key is omitted.
            serving = self._serving_fn()
            if serving is not None:
                record["serving"] = serving
        if self._quant_fn is not None:
            # quant block (ISSUE 14): the active inference dtype + the
            # interval's accuracy-probe aggregates. Before the sentinel
            # pass so the quant_divergence rule sees its own interval.
            quant = self._quant_fn()
            if quant is not None:
                record["quant"] = quant
        if self._replay_service_fn is not None:
            # elastic-fleet block (ISSUE 15): shard fill / spill health /
            # fan-out lag / membership leases. Before the sentinel pass
            # so the spill_thrash / fanout_lag / orphaned_slot rules see
            # their own interval.
            rs = self._replay_service_fn()
            if rs is not None:
                record["replay_service"] = rs
        if self._quality_fn is not None:
            # policy-quality block (ISSUE 20): eval return / Q-calibration /
            # shadow divergence / promotion state. Before the sentinel pass
            # so the quality_regression / canary_divergence / promotion_stall
            # rules see their own interval.
            quality = self._quality_fn()
            if quality is not None:
                record["quality"] = quality
        if self._recovery_fn is not None:
            # crash-recovery block (ISSUE 18): snapshot age / restore
            # counts / at-risk blocks / supervisor restarts. Before the
            # sentinel pass so the snapshot_stale / recovery_loop rules
            # see their own interval.
            recovery = self._recovery_fn()
            if recovery is not None:
                record["recovery"] = recovery
        if self._tracing_fn is not None:
            # cross-plane trace block (ISSUE 19): env-step -> gradient
            # latency with per-hop breakdown. Before the sentinel pass
            # so the e2e_latency_growth rule sees its own interval; an
            # interval that traced nothing returns None and the key is
            # omitted.
            trace = self._tracing_fn()
            if trace is not None:
                record["trace"] = trace
        if self._resources_fn is not None:
            # machine-side block (ISSUE 7): devices/host/buffer footprints
            # + the compile sub-block. Before the sentinel, which reads it.
            record["resources"] = self._resources_fn()
        if self._sentinel is not None:
            # the alert pass sees the COMPLETE record of its own interval
            # (throughput, health, learning, resources); firings also
            # append to alerts_player{p}.jsonl inside the engine
            record["alerts"] = self._sentinel.evaluate(record)
        if self._jsonl_path:
            with open(self._jsonl_path, "a") as f:
                f.write(json.dumps(record) + "\n")
        return record
