"""Spawned actor process entry point.

Kept import-light on purpose: with the ``spawn`` start method the child
re-imports this module before unpickling the target function, and the env
vars pinning JAX to the host CPU must be set before any jax import — the TPU
belongs to the learner process alone (the reference gets this isolation for
free from Ray's per-actor processes + CUDA_VISIBLE_DEVICES,
/root/reference/config.py:1).
"""

import os


def actor_process_main(cfg_dict: dict, player_idx: int, actor_idx: int,
                       epsilon: float, shm_name: str, queue, stop_event,
                       is_host: bool, port: int,
                       total_actors: int = None,
                       health_board=None, health_slot: int = None,
                       telemetry_board=None, serve_spec: dict = None,
                       generation: int = 0) -> None:
    # total_actors: the GLOBAL worker-fleet size for the vector ε ladder —
    # multihost spawners pass process_count * num_actors with a global
    # actor_idx; None = single-host (cfg.actor.num_actors)
    # A respawn dispatched just before shutdown can finish booting AFTER
    # the parent unlinked the weight/heartbeat segments — exit quietly
    # instead of dying loudly on a FileNotFoundError mid-bring-up.
    if stop_event.is_set():
        return
    # unconditional (not setdefault): an inherited JAX_PLATFORMS=tpu from a
    # TPU-pinned parent would otherwise have every actor child race to open
    # the single-process libtpu — the TPU belongs to the learner alone
    os.environ["JAX_PLATFORMS"] = "cpu"
    # late imports: only after the platform pin; jax.config route as well —
    # a wedged accelerator plugin can hang discovery despite the env var
    from r2d2_tpu.utils import pin_platform
    pin_platform()
    import jax
    import numpy as np

    from r2d2_tpu.config import Config
    from r2d2_tpu.models.network import NetworkApply
    from r2d2_tpu.runtime.actor_loop import make_actor_env, make_actor_policy
    from r2d2_tpu.runtime.weights import WeightSubscriber

    cfg = Config.from_dict(cfg_dict)
    seed = cfg.runtime.seed + 10_000 * player_idx + 100 * actor_idx
    # scalar or vectorized per cfg.actor.envs_per_actor — the shared
    # construction path (actor_loop.py) picks for env and policy alike
    env = make_actor_env(cfg, player_idx, actor_idx, seed,
                         is_host=is_host, port=port,
                         num_players=cfg.multiplayer.num_players)
    net = NetworkApply(env.action_space.n, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    sub = None
    serve_channel = None
    if cfg.actor.inference == "server" and serve_spec is not None:
        # served inference (ISSUE 13): this worker is a THIN client — no
        # local params, no weight subscriber. The channel rides the rung
        # the parent picked: the shm request ring handle crossed the
        # spawn boundary by name; socket just dials.
        params = None
        if serve_spec["transport"] == "shm":
            from r2d2_tpu.serve import ShmServeChannel
            serve_channel = ShmServeChannel(
                serve_spec["request_ring"], serve_spec["action_dim"],
                serve_spec["hidden_dim"],
                reply_slots=serve_spec["reply_slots"])
        elif serve_spec["transport"] == "socket_fleet":
            # sharded serving (ISSUE 17): one socket per fleet server,
            # routed client-id → shard → server off the shipped
            # assignment; MISROUTED bounces re-aim as the fleet churns
            from r2d2_tpu.serve import (RoutingChannel, ShardMap,
                                        SocketChannel)
            version, assign = serve_spec["assign"]
            smap = ShardMap(serve_spec["total_shards"], assign)
            smap.version = int(version)
            # eager dial on the bounded ladder (ISSUE 18): a server that
            # is still binding is retried with backoff; a misaddressed
            # one raises HERE with the real ECONNREFUSED instead of a
            # timeout storm at the first request
            serve_channel = RoutingChannel(
                {slot: SocketChannel(host, port, connect_retries=5,
                                     eager_connect=True)
                 for slot, (host, port) in serve_spec["servers"].items()},
                smap)
        else:
            from r2d2_tpu.serve import SocketChannel
            serve_channel = SocketChannel(serve_spec["host"],
                                          serve_spec["port"],
                                          connect_retries=5,
                                          eager_connect=True)
    else:
        params = net.init(jax.random.PRNGKey(cfg.runtime.seed))
        # quantized inference (ISSUE 14): the published tree is the
        # inference bundle — the subscriber template must match its
        # structure (a locally-quantized twin of the init params; the
        # policy swaps it for the learner's published twin on first poll)
        from r2d2_tpu.runtime.weights import make_publish_preparer
        prep = make_publish_preparer(net)
        if prep is not None:
            params = jax.device_get(prep(params, 0))
        try:
            sub = WeightSubscriber(shm_name, params)
        except FileNotFoundError:
            if stop_event.is_set():
                env.close()  # parent tore the segments down mid-boot
                return
            raise
        fresh = sub.poll()
        if fresh is not None:
            params = fresh
    # copy_updates=False: WeightSubscriber.poll materializes a fresh copy
    # per poll already — the policy may own those buffers directly
    policy, run_loop = make_actor_policy(cfg, net, params, actor_idx, seed,
                                         epsilon=epsilon,
                                         copy_updates=False,
                                         total_actors=total_actors,
                                         serve_channel=serve_channel,
                                         should_stop=stop_event.is_set)

    from r2d2_tpu.runtime.actor_loop import instrument_block_sink
    from r2d2_tpu.runtime.feeder import put_patient

    # health wiring: heartbeat per block emit + liveness touches while
    # parked under back-pressure, and fault injection for this slot —
    # same instrumentation point as the thread spawners (actor_loop.py).
    # health_slot is the fleet-local index (actor_idx is GLOBAL under a
    # multihost fleet); it defaults to actor_idx for single-host spawners.
    slot = actor_idx if health_slot is None else health_slot
    beat = ((lambda: health_board.touch(slot))
            if health_board is not None else None)

    # telemetry: this process's stage timers publish into its slot of the
    # shared board (the learner aggregates per log interval); spans drain
    # to a per-process JSONL next to the training logs. The board handle
    # crossed the spawn boundary by name, same lifecycle as the
    # heartbeat board.
    from r2d2_tpu.telemetry import Telemetry
    tele = Telemetry.from_config(
        cfg, name=f"actor-p{player_idx}-{actor_idx}",
        board=telemetry_board, slot=slot)
    if tele.enabled:
        # append: a supervisor respawn must not wipe the previous
        # incarnation's spans — the crash window is exactly what a
        # post-mortem trace export wants (the spawner truncates stale
        # files once per fresh run)
        tele.start_drain(os.path.join(
            cfg.runtime.save_dir or ".",
            f"spans_p{player_idx}_a{actor_idx}.jsonl"), append=True)

    sink = instrument_block_sink(
        cfg, slot,
        lambda b: put_patient(queue, b, stop_event.is_set, beat=beat,
                              telemetry=tele),
        board=health_board, telemetry=tele,
        # staleness stamp: the publish count of the params this actor is
        # acting with — the subscriber's last adopted version locally,
        # or (served) the server's adopted count riding each reply
        weight_version=((lambda: policy.weight_version)
                        if sub is None else (lambda: sub.publish_count)),
        # lane provenance (ISSUE 10): actor_idx is the GLOBAL worker
        # index (multihost fleets pass theirs), matching the ladder
        # layout vector_lane_epsilons spreads ε over
        lane_base=actor_idx * cfg.actor.envs_per_actor,
        # membership generation (ISSUE 15): an adopted slot's joiner
        # (generation > 0) must not inherit the slot's 'leave' fault
        generation=generation)

    from r2d2_tpu.tools.chaos import ChaosLeave
    try:
        run_loop(cfg, env, policy,
                 block_sink=sink,
                 weight_poll=(sub.poll if sub is not None
                              else (lambda: None)),
                 should_stop=stop_event.is_set,
                 telemetry=tele)
    except ChaosLeave:
        # deliberate departure (ISSUE 15 leave@block=N): exit 0 — the
        # elastic supervisor parks the slot for re-adoption; a loud
        # nonzero exit here would read as a crash in the logs
        pass
    except Exception:
        if not stop_event.is_set():
            raise      # a served policy raising at shutdown is clean-stop
    finally:
        tele.close()
        if sub is not None:
            sub.close()
        if serve_channel is not None:
            policy.close()
        # env is closed by the run loop (its finally owns it)
