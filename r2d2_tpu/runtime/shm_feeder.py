"""Native shared-memory experience transport (the plasma-store equivalent).

The reference moves experience blocks actor→buffer through Ray's plasma
object store — a C++ shared-memory store (/root/reference/worker.py:558,565).
``ShmBlockRing`` is this framework's native equivalent: a lock-free MPMC
ring (native/shm_ring.cc, Vyukov per-slot sequences) over one
``multiprocessing.shared_memory`` region. A fixed-shape Block crosses the
process boundary with ONE memcpy per side (fields stream straight into the
reserved slot) — no pickling, no pipe syscalls — where ``mp.Queue`` pickles
the multi-MB record and streams it through a pipe: measured 2.3x faster
per 3.3 MB reference-scale block same-process (1.95 vs 4.42 ms, PERF.md);
the gap widens under real contention since nothing serializes on pickle.

Duck-types the ``mp.Queue`` surface the feeder path uses (put/get/
get_nowait raising ``queue.Full``/``queue.Empty``), so ``put_patient`` and
``BlockQueue`` work unchanged. Picklable: spawned actor processes receive
the handle and lazily attach to the region by name.
"""

import queue as queue_mod
import time
from dataclasses import dataclass
from multiprocessing import shared_memory
from typing import List, Optional, Tuple

import numpy as np

from r2d2_tpu.replay.structs import Block, ReplaySpec, empty_block_np


def block_layout(spec: ReplaySpec,
                 tracing: bool = False) -> List[Tuple[str, tuple, np.dtype]]:
    """(field, shape, dtype) in serialization order — derived from the one
    authoritative record definition (empty_block_np) so it cannot drift.

    ``tracing`` (ISSUE 19) appends the lineage stamp field at the END, so
    a traced run's emission stamps survive the process boundary; off (the
    default), slot bytes are exactly the untraced layout — the ring a
    kill-switched run maps is byte-identical."""
    fields = [(k, v.shape, v.dtype) for k, v in empty_block_np(spec).items()]
    if tracing:
        fields.append(("trace_ms", (), np.dtype(np.int32)))
    return fields


@dataclass
class _Field:
    name: str
    shape: tuple
    dtype: np.dtype
    offset: int
    nbytes: int


class ShmBlockRing:
    """Bounded MPMC block queue in shared memory (see module docstring).

    The creating process owns the region (``close()`` unlinks it); unpickled
    copies in actor processes attach lazily on first use and only close
    their mapping.
    """

    def __init__(self, spec: ReplaySpec, maxsize: int = 64,
                 tracing: bool = False,
                 _attach_name: Optional[str] = None):
        self.spec = spec
        self.capacity = maxsize
        self.tracing = bool(tracing)
        self._fields: List[_Field] = []
        off = 0
        for name, shape, dtype in block_layout(spec, tracing=self.tracing):
            nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
            self._fields.append(_Field(name, shape, dtype, off, nbytes))
            off += nbytes
        self.slot_bytes = off
        self._owner = _attach_name is None
        self._shm = None
        self._base = 0
        if self._owner:
            from r2d2_tpu.native import ring_lib
            lib = ring_lib()
            size = int(lib.ring_required_bytes(self.capacity, self.slot_bytes))
            self._shm = shared_memory.SharedMemory(create=True, size=size)
            self._bind()
            lib.ring_init(self._base, self.capacity, self.slot_bytes)
        else:
            self._name = _attach_name   # lazy attach (child process)

    # -- pickling: handle crosses the process boundary, region does not --

    def __getstate__(self):
        return {"spec": self.spec, "capacity": self.capacity,
                "tracing": self.tracing, "name": self.name}

    def __setstate__(self, state):
        # .get: pre-tracing pickles (rings serialized before ISSUE 19)
        # attach with the untraced layout they were created with
        self.__init__(state["spec"], state["capacity"],
                      tracing=state.get("tracing", False),
                      _attach_name=state["name"])

    @property
    def name(self) -> str:
        return self._shm.name if self._shm is not None else self._name

    def _bind(self) -> None:
        import ctypes
        # keep the export object referenced: it pins the buffer address and
        # must be dropped before SharedMemory.close() (exported-pointer check)
        self._cbuf = ctypes.c_char.from_buffer(self._shm.buf)
        self._base = ctypes.addressof(self._cbuf)

    def _ensure(self):
        if self._shm is None:
            from r2d2_tpu.runtime.weights import untrack_attached_shm
            self._shm = shared_memory.SharedMemory(name=self._name)
            untrack_attached_shm(self._shm)
            self._bind()
        from r2d2_tpu.native import ring_lib
        return ring_lib()

    # -- serialization: fields stream directly into/out of the reserved
    # shm slot (reserve/commit API) — ONE memcpy per side total --

    def _slot_view(self, lib, pos: int) -> np.ndarray:
        off = int(lib.ring_payload_offset(self._base, pos))
        return np.ndarray((self.slot_bytes,), np.uint8, self._shm.buf, off)

    # -- mp.Queue surface (what put_patient / BlockQueue use) --

    def put(self, block: Block, timeout: Optional[float] = None) -> None:
        lib = self._ensure()
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            pos = int(lib.ring_reserve_push(self._base))
            if pos >= 0:
                break
            if deadline is None or time.monotonic() >= deadline:
                raise queue_mod.Full
            time.sleep(0.001)
        slot = self._slot_view(lib, pos)
        for f in self._fields:
            val = getattr(block, f.name)
            if val is None:        # unstamped block on a traced ring
                val = -1
            src = np.ascontiguousarray(val, f.dtype)
            slot[f.offset:f.offset + f.nbytes] = src.view(np.uint8).reshape(-1)
        lib.ring_commit_push(self._base, pos)

    def get_nowait(self) -> Block:
        lib = self._ensure()
        pos = int(lib.ring_reserve_pop(self._base))
        if pos < 0:
            raise queue_mod.Empty
        slot = self._slot_view(lib, pos)
        out = {}
        for f in self._fields:
            raw = slot[f.offset:f.offset + f.nbytes]
            out[f.name] = raw.view(f.dtype).reshape(f.shape).copy()
        lib.ring_commit_pop(self._base, pos)
        return Block(**out)

    def drain_stacked(self, max_items: int = 16) -> Tuple[Optional[Block], int]:
        """Non-blocking pop of up to ``max_items`` blocks into ONE stacked
        Block (leading K axis on every leaf). Each field streams straight
        from its shm ring slot into row k of a contiguous preallocated
        stacked array — no intermediate per-block arrays, no Python-level
        restacking — so the result is device_put-ready as a single
        transfer. Returns (stacked_block, k); (None, 0) when empty."""
        lib = self._ensure()
        out = None
        k = 0
        for _ in range(max_items):
            pos = int(lib.ring_reserve_pop(self._base))
            if pos < 0:
                break
            if out is None:
                out = {f.name: np.empty((max_items,) + f.shape, f.dtype)
                       for f in self._fields}
            slot = self._slot_view(lib, pos)
            for f in self._fields:
                raw = slot[f.offset:f.offset + f.nbytes]
                out[f.name][k] = raw.view(f.dtype).reshape(f.shape)
            lib.ring_commit_pop(self._base, pos)
            k += 1
        if k == 0:
            return None, 0
        if k < max_items:
            # contiguous prefix view — no copy
            out = {name: arr[:k] for name, arr in out.items()}
        return Block(**out), k

    def get(self, timeout: Optional[float] = None) -> Block:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                return self.get_nowait()
            except queue_mod.Empty:
                if deadline is None or time.monotonic() >= deadline:
                    raise
                time.sleep(0.001)

    def qsize(self) -> int:
        lib = self._ensure()
        return int(lib.ring_size(self._base))

    def recover_stalled(self, stale_ms: int = 5000) -> int:
        """Free head slots wedged by a producer that died between reserve
        and commit (see shm_ring.cc). Call after reaping a dead actor
        process — the staleness grace protects any live writer, whose
        memcpy takes milliseconds, not seconds. Returns slots freed."""
        lib = self._ensure()
        return int(lib.ring_recover_stalled(self._base, stale_ms))

    def close(self) -> None:
        if self._shm is None:
            return
        self._base = 0
        self._cbuf = None   # release the exported pointer before close()
        self._shm.close()
        if self._owner:
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass
        self._shm = None
