"""Process/host runtime: everything the reference delegates to Ray
(/root/reference/train.py:23, worker.py:29,251,502) rebuilt without it.

* weights.py    — seqlock shared-memory weight service (replaces the plasma
                  object-store weight publication, worker.py:286-290,572-576);
* feeder.py     — actor→learner experience transport (replaces remote
                  ReplayBuffer.add RPCs, worker.py:558,565);
* metrics.py    — reference-log-compatible training metrics (worker.py:220-234);
* checkpoint.py — orbax checkpoint of (params, opt_state, step, env_steps)
                  with the reference's weights-only warm-start (SURVEY §5.4);
* learner_loop.py / actor_loop.py / orchestrator.py — the Learner/Actor/train()
  trio (worker.py:251-390,502-591, train.py:21-66) as plain processes/threads.
"""

from r2d2_tpu.runtime.weights import (InProcWeightStore, WeightPublisher,
                                      WeightSubscriber,
                                      make_publish_preparer, wrap_publish)
from r2d2_tpu.runtime.feeder import BlockQueue
from r2d2_tpu.runtime.metrics import TrainMetrics
from r2d2_tpu.runtime.learner_loop import Learner
from r2d2_tpu.runtime.actor_loop import run_actor
from r2d2_tpu.runtime.orchestrator import train

__all__ = [
    "InProcWeightStore", "WeightPublisher", "WeightSubscriber",
    "BlockQueue", "TrainMetrics", "Learner", "run_actor", "train",
    "make_publish_preparer", "wrap_publish",
]
