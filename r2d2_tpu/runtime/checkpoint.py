"""Checkpoint / resume (ref /root/reference/worker.py:311,380-381 +
SURVEY §5.4).

The reference torch.saves ``(state_dict, training_steps, env_steps)`` every
``save_interval`` learner steps and warm-starts weights-only via
``config.pretrain``. Here the full training state — params, target params,
optimizer state, step, env_steps — goes through orbax (atomic directory
writes, async-safe), and ``load_pretrain`` reproduces the weights-only
warm-start path for both learner and actors.

Checkpoint k lives at ``{save_dir}/{game}{k}_player{p}`` mirroring the
reference's ``{game}{k}_player{p}.pth`` naming (worker.py:381) so evaluation
sweeps iterate checkpoints the same way (test.py:30-32).
"""

import os
import re
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp


def _ckpt_dir(save_dir: str, game: str, index: int, player: int) -> str:
    return os.path.abspath(os.path.join(save_dir, f"{game}{index}_player{player}"))


def _solo_checkpointer() -> ocp.Checkpointer:
    """A checkpointer whose barrier set is ONLY the calling process.

    Under a multi-controller job (jax.process_count() > 1) orbax's default
    save synchronizes across every process — but the lockstep multihost
    trainer (parallel/multihost.py) checkpoints on rank 0 only, and the
    other ranks never enter the save, so the default barrier deadlocks
    (observed: loopback demo wedged at the first save boundary)."""
    if jax.process_count() > 1:
        me = jax.process_index()
        return ocp.Checkpointer(
            ocp.PyTreeCheckpointHandler(),
            multiprocessing_options=ocp.options.MultiprocessingOptions(
                primary_host=me, active_processes={me},
                barrier_sync_key_prefix=f"solo{me}"))
    return ocp.PyTreeCheckpointer()


def save_checkpoint(save_dir: str, game: str, index: int, player: int,
                    params, opt_state, target_params, step: int,
                    env_steps: int, config_json: Optional[str] = None) -> str:
    path = _ckpt_dir(save_dir, game, index, player)
    ckptr = _solo_checkpointer()
    payload = {
        "params": jax.device_get(params),
        "target_params": jax.device_get(target_params),
        "opt_state": jax.device_get(opt_state),
        "step": np.asarray(step, np.int64),
        "env_steps": np.asarray(env_steps, np.int64),
    }
    ckptr.save(path, payload, force=True)
    if config_json is not None:
        # the training Config rides next to the weights so evaluation can
        # rebuild the exact network (the reference's checkpoints silently
        # depend on config.py not having changed since training)
        with open(path + ".config.json", "w") as f:
            f.write(config_json)
    return path


def load_checkpoint_config(path: str):
    """Config stored by save_checkpoint, or None for config-less checkpoints."""
    cfg_path = os.path.abspath(path) + ".config.json"
    if not os.path.exists(cfg_path):
        return None
    from r2d2_tpu.config import Config
    with open(cfg_path) as f:
        return Config.from_json(f.read())


def restore_checkpoint(path: str, template: Optional[Dict[str, Any]] = None
                       ) -> Dict[str, Any]:
    ckptr = ocp.PyTreeCheckpointer()
    try:
        if template is not None:
            return ckptr.restore(os.path.abspath(path), item=template)
        return ckptr.restore(os.path.abspath(path))
    except (ValueError, KeyError, TypeError) as e:
        # orbax structure mismatches surface as opaque tree errors; name the
        # most likely cause (the checkpoint predates an architecture change
        # — e.g. the round-3 LSTM param-tree rename) and the escape hatch
        raise ValueError(
            f"checkpoint at {path!r} does not match the current network's "
            "parameter tree — it was likely saved by an older architecture "
            "revision (parameter names/shapes changed). Re-train, or "
            "restore with an explicitly matching template. If the only "
            "change is flipping network.space_to_depth, migrate the params "
            "with r2d2_tpu.models.network.convert_params_space_to_depth "
            "(the runtime.pretrain warm-start path migrates "
            "automatically).\n"
            f"original error: {type(e).__name__}: {e}") from e


def _maybe_migrate_space_to_depth(params, params_template):
    """Auto-migrate a standard-layout checkpoint to the space_to_depth
    layout when the template expects it (round-3 advisor: warm-starting
    with network.space_to_depth=on from an off-layout run previously died
    with the generic mismatch error, never mentioning the exact-rewrite
    migration that exists). The reverse direction is refused loudly —
    downgrading a layout silently would be surprising."""
    try:
        t_kernel = np.asarray(
            params_template["params"]["torso"]["Conv_0"]["kernel"])
        p_kernel = np.asarray(params["params"]["torso"]["Conv_0"]["kernel"])
    except (KeyError, TypeError):
        return params                     # unfamiliar tree: leave untouched
    if t_kernel.shape == p_kernel.shape:
        return params
    tkh, tkw, tc, to = t_kernel.shape
    pkh, pkw, pc, po = p_kernel.shape
    if (tc, tkh, tkw) == (4 * pc, pkh // 2, pkw // 2) and to == po:
        import logging
        from r2d2_tpu.models.network import convert_params_space_to_depth
        logging.getLogger(__name__).info(
            "pretrain checkpoint uses the standard first-conv layout; "
            "auto-migrating to space_to_depth (exact rewrite)")
        return convert_params_space_to_depth(params, frame_stack=pc)
    if (pc, pkh, pkw) == (4 * tc, tkh // 2, tkw // 2):
        raise ValueError(
            "pretrain checkpoint uses the space_to_depth first-conv layout "
            "but the current network has network.space_to_depth=off — set "
            "it to 'on' (the transform is exact; there is no automatic "
            "downgrade)")
    return params


def load_pretrain(path: str, params_template):
    """Weights-only warm start (ref worker.py:260-261,511-512): restores just
    ``params`` from a checkpoint directory, leaving optimizer/step fresh.
    A standard-layout checkpoint loaded into a space_to_depth network is
    migrated automatically (exact rewrite; see convert_params_space_to_depth)."""
    restored = restore_checkpoint(path)
    params = restored["params"] if isinstance(restored, dict) else restored
    params = _maybe_migrate_space_to_depth(params, params_template)

    # conform dtypes to the template; shape mismatches fail HERE with the
    # param's path named instead of surfacing later inside apply
    def conform(path_parts, t, p):
        t_arr, p_arr = np.asarray(t), np.asarray(p)
        if t_arr.shape != p_arr.shape:
            name = "/".join(str(getattr(k, "key", k)) for k in path_parts)
            raise ValueError(
                f"pretrain param {name!r} has shape {p_arr.shape}; the "
                f"current network expects {t_arr.shape} — architecture "
                "mismatch (network config differs from the checkpoint's)")
        return np.asarray(p_arr, t_arr.dtype)

    return jax.tree_util.tree_map_with_path(conform, params_template, params)


def resume_training_state(path: str, train_state):
    """Full resume (SURVEY §5.4): restore params, target_params, opt_state,
    step, and env_steps from a checkpoint into ``train_state``. Returns
    ``(new_train_state, env_steps)``. The RNG key is NOT checkpointed (the
    reference checkpoints no RNG either) — the carried key stays fresh."""
    template = {
        "params": jax.device_get(train_state.params),
        "target_params": jax.device_get(train_state.target_params),
        "opt_state": jax.device_get(train_state.opt_state),
        "step": np.asarray(0, np.int64),
        "env_steps": np.asarray(0, np.int64),
    }
    restored = restore_checkpoint(path, template)
    import jax.numpy as jnp
    new_state = train_state.replace(
        params=restored["params"],
        target_params=restored["target_params"],
        opt_state=restored["opt_state"],
        step=jnp.asarray(int(restored["step"]), jnp.int32),
    )
    return new_state, int(restored["env_steps"])


def apply_restore(runtime_cfg, train_state) -> Tuple[Any, int]:
    """The one resume/warm-start policy, shared by the single-host Learner
    and the multihost lockstep trainer (so the rank-sensitive details —
    mutual exclusion, the pretrain target-params copy — cannot diverge).
    Returns ``(train_state, resumed_env_steps)``; a no-op without
    runtime.resume/pretrain."""
    if runtime_cfg.resume and runtime_cfg.pretrain:
        raise ValueError(
            "runtime.resume and runtime.pretrain are mutually exclusive — "
            "resume restores the full training state")
    if runtime_cfg.resume:
        return resume_training_state(runtime_cfg.resume, train_state)
    if runtime_cfg.pretrain:
        params = load_pretrain(runtime_cfg.pretrain, train_state.params)
        return train_state.replace(
            params=params,
            target_params=jax.tree_util.tree_map(np.copy, params)), 0
    return train_state, 0


def list_checkpoints(save_dir: str, game: str, player: int
                     ) -> List[Tuple[int, str]]:
    """Sorted (index, path) pairs, the eval sweep's iteration order
    (ref test.py:30-32)."""
    if not os.path.isdir(save_dir):
        return []
    pat = re.compile(re.escape(game) + r"(\d+)_player" + str(player) + r"$")
    out = []
    for name in os.listdir(save_dir):
        m = pat.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(save_dir, name)))
    return sorted(out)


def latest_checkpoint(save_dir: str, game: str, player: int
                      ) -> Optional[str]:
    """Path of the newest checkpoint, or None — the supervisor's resume
    target (runtime/supervisor.py picks up from here after a crash)."""
    ckpts = list_checkpoints(save_dir, game, player)
    return ckpts[-1][1] if ckpts else None


def prune_checkpoints(save_dir: str, game: str, player: int,
                      keep: int) -> List[str]:
    """Retention GC (ISSUE 18 satellite): delete all but the newest
    ``keep`` checkpoint directories for one player, each with its
    ``.config.json`` sidecar. Runs after every save — before this, disk
    growth was unbounded (every orbax dir holds the full param + opt
    tree). ``keep <= 0`` keeps everything. Returns the pruned paths.

    The rolling replay snapshot (replay/snapshot.py) is NOT pruned: it
    is one overwritten-in-place pair per player, not a per-checkpoint
    set, and the newest checkpoint resumes from it."""
    import shutil
    if keep <= 0:
        return []
    pruned = []
    for _idx, path in list_checkpoints(save_dir, game, player)[:-keep]:
        shutil.rmtree(path, ignore_errors=True)
        try:
            os.remove(path + ".config.json")
        except OSError:
            pass
        pruned.append(path)
    return pruned
