"""Colocated act+train loop for the fully on-device acting path.

The orchestrator's host loop (runtime/orchestrator.py) spawns an actor
FLEET: threads/processes stepping Python envs, blocks crossing a queue,
weights crossing a shm service. This loop replaces all of it with a
single-threaded alternation on the device mesh (Podracer "Anakin", arxiv
2104.06272):

    act segment  — one jitted lax.scan: block_length steps of
                   actor.anakin_lanes batched pure-JAX envs + the policy
                   forward + in-graph block assembly (actor/anakin.py);
    ring-write   — the segment's N stacked blocks enter device replay via
                   the existing donated ``replay_add_many`` dispatch;
    train        — the learner's fused step(s), exactly as the host loop
                   dispatches them (same Learner, same diagnostics).

Mesh composition (ISSUE 8): with ``mesh.dp > 1`` the act segment and the
ring-write fuse into ONE shard_map dispatch over the Learner's mesh
(parallel/sharded.py make_sharded_anakin_act) — the lanes partition into
dp per-shard groups, each acting with its own RNG chain and its slice of
the GLOBAL ε ladder, writing straight into its local replay shard; the
learner's dp-sharded step then trains on the same mesh. Aggregate acting
throughput scales with dp while the learner gains its sharded-batch
throughput (PERF.md round 12). Only ``mesh.mp > 1`` and multihost remain
out of scope for the fused loop.

Weights are published BY REFERENCE: each acting segment reads
``learner.train_state.params`` directly — no weight service, no copy, and
the actors are never more than one segment stale. Staleness accounting
(PR5) keeps working: blocks are stamped with a pseudo publish count that
advances every ``weight_publish_interval`` learner steps, the same clock a
WeightPublisher would have ticked, and ``Learner.flush_metrics`` reads the
same counter — so sample-age and replay-occupancy ages stay meaningful.

Everything host-side is bookkeeping at SEGMENT cadence (N blocks, N*L env
steps at a time): ring accounting, the replay rate limiter, TrainMetrics,
telemetry stage timers (the new 'actor/act_scan' stage + the existing
ingest/learner stages), checkpoints. Episode returns are summed on device
and fetched lazily at log time. The loop is single-threaded and therefore
DETERMINISTIC given seeds — the collect:learn interleave is pinned by
``actor.anakin_scans_per_train`` (plus the rate limiter), not by host
scheduling.
"""

import os
import time
from typing import Callable, List, Optional

import jax
import numpy as np

from r2d2_tpu.config import Config, apex_epsilon
from r2d2_tpu.models.network import NetworkApply
from r2d2_tpu.replay.device_replay import replay_add_many
from r2d2_tpu.runtime.learner_loop import Learner
from r2d2_tpu.runtime.metrics import TrainMetrics


class AnakinStack:
    """Duck-typed PlayerStack twin for the on-device path: the pieces the
    callers actually touch (learner/metrics/telemetry + close())."""

    def __init__(self, cfg: Config, learner: Learner, metrics: TrainMetrics,
                 telemetry, carry):
        self.cfg = cfg
        self.player_idx = 0
        self.learner = learner
        self.metrics = metrics
        self.telemetry = telemetry
        self.carry = carry       # final ActCarry (inspection/tests)

    def close(self) -> None:
        self.learner.stop_background()
        self.telemetry.close()


def run_anakin_train(cfg: Config, *, max_training_steps: Optional[int] = None,
                     max_seconds: Optional[float] = None,
                     log_fn: Optional[Callable[[dict], None]] = None
                     ) -> List[AnakinStack]:
    """Run the fused act+train loop; returns [stack] (the Learner holds
    final state) — the same contract as orchestrator.train, which
    delegates here when ``actor.on_device`` is set."""
    from r2d2_tpu.actor.anakin import init_act_carry, make_anakin_act
    from r2d2_tpu.envs.factory import create_jax_env
    from r2d2_tpu.telemetry import Telemetry

    if not cfg.actor.on_device:
        raise ValueError("run_anakin_train requires actor.on_device=True")
    n_dev = len(jax.devices())
    dp = cfg.mesh.resolved_dp(n_dev)
    num_lanes = cfg.actor.anakin_lanes
    if cfg.mesh.mp > 1:
        raise NotImplementedError(
            "actor.on_device composes with data-parallel meshes only: the "
            "fused acting scan runs per-shard lane groups over mesh.dp, "
            "but model parallelism (mesh.mp > 1) shards the network's "
            "feature dims through the GSPMD learner step, which the "
            "acting scan does not run under — set mesh.mp=1 (mesh.dp > 1 "
            "is fine) or actor.on_device=false")
    if cfg.mesh.multihost:
        raise NotImplementedError(
            "actor.on_device is single-controller only: the fused loop "
            "owns the whole mesh from one process, while "
            "mesh.multihost=True runs the lockstep per-host trainer "
            "(parallel/multihost.py) — unset mesh.multihost, or use the "
            "host actor fleet for multihost runs")
    # the lane/shard contracts again, against the RESOLVED dp — Config
    # enforces both at construction for explicit mesh.dp, but dp=-1
    # (all devices) only resolves here
    if num_lanes % dp != 0:
        raise ValueError(
            f"actor.anakin_lanes ({num_lanes}) must be divisible by the "
            f"resolved mesh.dp ({dp}): each shard owns an equal lane "
            "group (anakin_lanes % dp == 0) — adjust actor.anakin_lanes "
            "or mesh.dp")

    env = create_jax_env(cfg.env)
    net = NetworkApply(env.action_dim, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)

    metrics = TrainMetrics(0, cfg.runtime.save_dir,
                           resume=bool(cfg.runtime.resume))
    telemetry = Telemetry.from_config(cfg, name="anakin-p0")
    metrics.set_telemetry(telemetry)
    if cfg.telemetry.enabled:
        telemetry.start_drain(
            os.path.join(cfg.runtime.save_dir or ".", "spans_player0.jsonl"),
            append=bool(cfg.runtime.resume))

    learner = Learner(cfg, net, 0, metrics=metrics)
    spec = learner.spec
    seg_steps = spec.block_length          # learning steps per lane-block
    if num_lanes // dp > spec.num_blocks:
        raise ValueError(
            f"per-shard lane group ({num_lanes // dp} = {num_lanes} lanes "
            f"/ dp={dp}) must be <= num_blocks ({spec.num_blocks}): grow "
            "replay.capacity or lower actor.anakin_lanes")
    pub_interval = max(cfg.runtime.weight_publish_interval, 1)

    def publish_count() -> int:
        # by-reference publication clock: what a WeightPublisher would
        # have counted had the learner pushed params every
        # weight_publish_interval steps (1 = the initial params)
        return 1 + learner.training_steps // pub_interval

    learner.weight_version_fn = publish_count

    # quantized acting (ISSUE 14): the by-reference pseudo-clock also
    # drives publish-time quantization — the inference bundle is rebuilt
    # only when the pseudo publish count TICKS (every
    # weight_publish_interval learner steps), never per segment, so the
    # acting scan streams a publish-time twin exactly like the host
    # actors do (no hot-path requantization). At "f32" the segment keeps
    # reading learner.train_state.params by reference, byte-identical.
    from r2d2_tpu.runtime.weights import make_publish_preparer
    prep = make_publish_preparer(net)
    quant_stats = None
    if prep is not None:
        from r2d2_tpu.telemetry import QuantStats
        quant_stats = QuantStats(cfg.network.inference_dtype,
                                 cfg.telemetry.quant_probe_interval)
        metrics.set_quant(quant_stats.interval_block)
    _bundle = {"tree": None, "pub": -1}

    def acting_params():
        if prep is None:
            return learner.train_state.params
        pc = publish_count()
        if _bundle["tree"] is None or _bundle["pub"] != pc:
            _bundle["tree"] = prep(learner.train_state.params, pc)
            _bundle["pub"] = pc
            quant_stats.on_stamp(pc)
        return _bundle["tree"]

    # the ε ladder spans the GLOBAL lane count whatever the mesh: dp
    # changes where lanes run, never the Ape-X exploration schedule
    epsilons = [apex_epsilon(i, num_lanes, cfg.actor.base_eps,
                             cfg.actor.eps_alpha) for i in range(num_lanes)]
    act_key = jax.random.PRNGKey(cfg.runtime.seed + 17)
    if dp > 1:
        # sharded anakin (ISSUE 8): the act scan + per-shard ring-write
        # fused into ONE shard_map dispatch over the Learner's mesh —
        # each shard's lane group feeds its local replay shard directly,
        # alongside the same mesh's dp-sharded learner step
        from r2d2_tpu.parallel import (init_sharded_act_carry,
                                       make_sharded_anakin_act)
        act_fn = make_sharded_anakin_act(
            env, net, spec, mesh=learner.mesh, num_lanes=num_lanes,
            epsilons=epsilons, gamma=cfg.optim.gamma,
            priority=cfg.actor.anakin_priority,
            near_greedy_eps=cfg.actor.near_greedy_eps,
            priority_eta=cfg.optim.priority_eta,
            quant_probe=cfg.telemetry.quant_probe_interval > 0)
        carry = init_sharded_act_carry(env, spec, num_lanes, learner.mesh,
                                       act_key)
    else:
        act_fn = make_anakin_act(
            env, net, spec, num_lanes=num_lanes, epsilons=epsilons,
            gamma=cfg.optim.gamma, priority=cfg.actor.anakin_priority,
            near_greedy_eps=cfg.actor.near_greedy_eps,
            priority_eta=cfg.optim.priority_eta,
            quant_probe=cfg.telemetry.quant_probe_interval > 0)
        carry = init_act_carry(env, spec, num_lanes, act_key)

    # system-health pillar (ISSUE 7), the on-device twin of the
    # PlayerStack wiring: resource sampler (the Learner registered ring +
    # train-state footprints; the lane carry registers here), the compile/
    # retrace monitor, and the alert engine. No actor fleet, so no board
    # gauges — this process's RSS/CPU is the whole host picture.
    resources = None
    compile_mon = None
    if cfg.telemetry.enabled and cfg.telemetry.resources_enabled:
        from r2d2_tpu.telemetry import (AlertEngine, CompileMonitor,
                                        ResourceMonitor, active_monitor,
                                        default_rules)
        from r2d2_tpu.telemetry.resources import (pytree_nbytes,
                                                  register_buffer)
        register_buffer("p0/anakin_carry", pytree_nbytes(carry))
        if cfg.telemetry.compile_enabled and active_monitor() is None:
            compile_mon = CompileMonitor().install()
        resources = ResourceMonitor(
            0, cfg.runtime.save_dir or ".",
            interval_s=cfg.telemetry.resources_interval_s,
            headroom_warn_frac=cfg.telemetry.resources_headroom_warn_frac,
            compile_monitor=compile_mon,
            aot_coverage_fn=learner.aot_coverage)
        metrics.set_resources(resources.block)
        if cfg.telemetry.alerts_enabled:
            metrics.set_sentinel(AlertEngine(
                default_rules(cfg.telemetry),
                jsonl_path=os.path.join(cfg.runtime.save_dir or ".",
                                        "alerts_player0.jsonl"),
                resume=bool(cfg.runtime.resume)))

    pending_stats: list = []

    def act_segment():
        nonlocal carry
        t0 = time.time()
        if dp > 1:
            # act + ring-write fused in one sharded dispatch: each
            # shard's blocks land in its local replay without ever
            # leaving the shard, so there is no separate commit stage
            carry, learner.replay_state, stats = act_fn(
                acting_params(), carry, learner.replay_state,
                np.int32(publish_count()))
            t1 = t2 = time.time()
        else:
            carry, blocks, stats = act_fn(
                acting_params(), carry,
                np.int32(publish_count()))
            t1 = time.time()
            learner.replay_state = replay_add_many(
                spec, learner.replay_state, blocks)
            t2 = time.time()
            # commit latency only (t2-t1): the acting dispatch is its
            # own stage; folding it in would make ingest_drain_latency_ms
            # incomparable with the host path's pop-to-commit reading
            telemetry.observe("ingest/commit", t2 - t1)
        telemetry.observe("actor/act_scan", t1 - t0)
        telemetry.record_span("actor/act_scan", t0, t1,
                              {"lanes": num_lanes, "steps": seg_steps,
                               "shards": dp})
        wv = publish_count()
        for _ in range(num_lanes):
            learner.ring.advance(seg_steps, wv)
            metrics.on_block(seg_steps, None)
        learner.env_steps += num_lanes * seg_steps
        metrics.set_buffer_size(learner.ring.buffer_steps)
        metrics.on_ingest_drain(num_lanes, t2 - t1)
        pending_stats.append(stats)

    def flush_stats():
        if not pending_stats:
            return
        fetched = jax.device_get(pending_stats)
        pending_stats.clear()
        # per-shard interval reductions (dp=1 stats are scalars — one
        # "shard"): episode counts/returns feed the return average, the
        # per-shard rows + imbalance ratio feed the record's anakin
        # block (telemetry/alerts.py shard_imbalance, inspect.py panel)
        eps_counts = np.sum([np.atleast_1d(s["reported_episodes"])
                             for s in fetched], axis=0)
        ret_sums = np.sum([np.atleast_1d(s["reported_return_sum"])
                           for s in fetched], axis=0)
        episodes = np.sum([np.atleast_1d(s["episodes"])
                           for s in fetched], axis=0)
        metrics.on_episodes(int(eps_counts.sum()), float(ret_sums.sum()))
        if quant_stats is not None and "quant_dq" in fetched[0]:
            # one probe per segment (per shard under dp > 1): interval
            # max |ΔQ| and the lane-weighted mean agreement feed the
            # record's quant block like the host actors' probes
            for s in fetched:
                quant_stats.on_probe(
                    float(np.max(np.atleast_1d(s["quant_dq"]))),
                    float(np.mean(np.atleast_1d(s["quant_agree"]))),
                    lanes=num_lanes)
        if dp > 1:
            shard_env = np.sum([np.atleast_1d(s["env_steps"])
                                for s in fetched], axis=0)
        else:
            shard_env = np.asarray([len(fetched) * num_lanes * seg_steps])
        lo = float(shard_env.min())
        metrics.set_anakin({
            "dp": dp,
            "lanes_per_shard": num_lanes // dp,
            "shard_env_steps": [int(v) for v in shard_env],
            "shard_episodes": [int(v) for v in episodes],
            "shard_reported_episodes": [int(v) for v in eps_counts],
            "shard_return_sum": [round(float(v), 4) for v in ret_sums],
            "shard_imbalance": (round(float(shard_env.max()) / lo, 4)
                                if lo > 0 else None),
        })

    # mid-run profiler capture (ISSUE 9 satellite): the fused on-device
    # loop is the exact path the kernel campaign profiles, yet only the
    # host-actor orchestrator had the capture triggers — wire the SAME
    # three (first-interval profile_dir, one-shot profile_at_step,
    # SIGUSR2 on demand) via the shared CaptureTriggers helper, so the
    # subtle arming/pending/restore rules exist once. Captures land
    # where telemetry/traceparse.py expects them.
    from r2d2_tpu.telemetry.profiler import CaptureTriggers
    triggers = CaptureTriggers(cfg.runtime)

    start = time.time()
    deadline = start + max_seconds if max_seconds else None
    max_steps = max_training_steps or cfg.optim.training_steps
    last_log = start
    stack = AnakinStack(cfg, learner, metrics, telemetry, carry)
    try:
        triggers.install()
        triggers.start_first_interval()
        if cfg.runtime.save_interval:
            learner.save(0)
        while ((deadline is None or time.time() < deadline)
               and learner.training_steps < max_steps):
            if learner.ingestion_paused:
                # rate limiter: collection is ahead of the collect:learn
                # budget; only train until it reopens (the gate cannot be
                # closed here — paused implies it is open)
                learner._note_pause(True)
            else:
                learner._note_pause(False)
                scans = (cfg.actor.anakin_scans_per_train
                         if learner.ready else 1)
                for _ in range(scans):
                    act_segment()
            if learner.ready and learner.training_steps < max_steps:
                learner.step()
            now = time.time()
            triggers.poll(now, learner.training_steps)
            if resources is not None:
                # resource sampling rides the loop at the same cheap-time-
                # check cadence the PlayerStack's supervise pass uses
                resources.maybe_sample(now)
            if compile_mon is not None and learner.training_steps:
                # warm-up ends once training has started: act_fn and the
                # train program have compiled; any further compile of a
                # known fn with new avals is a retrace (idempotent latch)
                compile_mon.mark_warm()
            if now - last_log >= cfg.runtime.log_interval:
                learner.flush_metrics()
                flush_stats()
                record = metrics.log(now - last_log)
                if log_fn:
                    log_fn({"player": 0, **record})
                last_log = now
        learner.flush_metrics()
        flush_stats()
    finally:
        triggers.uninstall()   # stop any live capture, restore SIGUSR2
        stack.carry = carry
        try:
            if cfg.runtime.save_interval:
                learner.save_final()
        except Exception:
            import logging
            logging.getLogger(__name__).exception("final checkpoint failed")
        stack.close()
        if compile_mon is not None:
            # restore the pxla logger exactly and release the process-
            # global active-monitor slot (same contract as PlayerStack)
            compile_mon.uninstall()
    return [stack]
