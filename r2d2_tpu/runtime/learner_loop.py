"""Learner host driver around the fused device step.

The reference Learner is a Ray GPU actor with a prefetch thread pulling
batches over RPC and a train thread running torch ops
(/root/reference/worker.py:251-390). Two placements here
(config replay.placement):

  * "device" (default): batches never cross the host boundary — the fused
    step samples in HBM — so the host loop is thin: drain the feeder queue
    (jitted ring-writes), gate on learning_starts, dispatch steps, publish
    weights, checkpoint, count metrics. Ingestion between steps is the only
    add/sample interleaving point, which is what makes the fused step's
    priority write-back race-free (see replay/device_replay.py).
  * "host": the reference's architecture minus Ray — numpy ring + native C++
    sum tree on the CPU, a prefetch thread keeping ``prefetch_batches``
    device-resident batches in flight (ref worker.py:292-306), and an async
    priority write-back thread guarded by the staleness check
    (ref worker.py:368,192-209).
"""

import queue as queue_mod
import threading
import time
from typing import Callable, List, Optional

import jax
import numpy as np

from r2d2_tpu.config import Config
from r2d2_tpu.learner.train_step import (
    TrainState, create_train_state, make_external_batch_step,
    make_learner_step, make_multi_learner_step)
from r2d2_tpu.models.network import NetworkApply
from r2d2_tpu.replay.device_replay import (
    replay_add, replay_add_many, replay_init)
from r2d2_tpu.replay.host_replay import HostReplay
from r2d2_tpu.replay.structs import Block, ReplaySpec
from r2d2_tpu.runtime.checkpoint import apply_restore, save_checkpoint
from r2d2_tpu.runtime.metrics import TrainMetrics


class Learner:
    def __init__(self, cfg: Config, net: NetworkApply, player_idx: int = 0,
                 seed: Optional[int] = None, metrics: Optional[TrainMetrics] = None):
        self.cfg = cfg
        self.net = net
        self.player_idx = player_idx
        self.spec = ReplaySpec.from_config(cfg)
        seed = cfg.runtime.seed if seed is None else seed
        key = jax.random.PRNGKey(seed + 1000 * player_idx)

        self.train_state = create_train_state(key, net, cfg.optim)
        self.train_state, resumed_env_steps = apply_restore(
            cfg.runtime, self.train_state)
        self.host_mode = cfg.replay.placement == "host"
        self.mesh = None
        # learning-dynamics diagnostics (ISSUE 5): a LearningDiag fuses
        # the diagnostic outputs into the jitted step; None (the
        # telemetry.learning_enabled kill switch) compiles the
        # pre-diagnostics program byte-for-byte. The aggregator holds the
        # per-dispatch device outputs and builds the periodic record's
        # 'learning' block (and owns the NaN forensics) at flush.
        from r2d2_tpu.telemetry.learning import (LearningAggregator,
                                                 LearningDiag)
        self._diag = LearningDiag.from_config(cfg)
        self._learning_agg = (LearningAggregator(
            player_idx, cfg.runtime.save_dir, cfg.telemetry.nan_policy,
            cfg.optim.lr) if self._diag is not None else None)
        # replay & data-pathology pillar (ISSUE 10): same spec/aggregator
        # pattern — a ReplayDiag fuses sum-tree health, sample-lifetime
        # accounting and lane composition into the step; None (the
        # telemetry.replay_diag_enabled kill switch, mirrored by
        # spec.replay_diag for the ring-state allocation) compiles the
        # pre-pillar program and the record carries no replay_diag block.
        from r2d2_tpu.telemetry.replaydiag import (ReplayDiag,
                                                   ReplayDiagAggregator)
        self._rdiag = ReplayDiag.from_config(cfg)
        self._replay_agg = (ReplayDiagAggregator(self._rdiag.lanes)
                            if self._rdiag is not None else None)
        # wired by the orchestrator alongside `publish`: () -> the weight
        # service's current publish count — the learner half of the
        # sample-age clock (None = ages reported as unknown)
        self.weight_version_fn: Optional[Callable[[], int]] = None
        # -- disaggregated replay service (ISSUE 15) --
        # fleet.replay_shards >= 1 routes ingestion through N
        # addressable device shards (fleet/replay_service.py: the
        # dp-sharded rings generalized, plus the host-RAM spill tier)
        # and trains through the EXTERNAL-BATCH step on service-sampled
        # prioritized batches — the consumer draws from the service
        # instead of fusing sample+train over one in-mesh ring, which is
        # what lets producers/consumers/storage stop sharing a program.
        self.service = None
        self._exp_trace = None
        if cfg.fleet.replay_shards >= 1 and not self.host_mode:
            import dataclasses

            from r2d2_tpu.fleet.replay_service import ReplayService
            # equal device-ring slices per shard; the fused-path replay
            # diagnostics state stays off (the service's own telemetry
            # block carries shard/spill health; the external-batch
            # step's batch-side rdiag — lane composition — still runs)
            shard_spec = dataclasses.replace(
                self.spec,
                num_blocks=self.spec.num_blocks // cfg.fleet.replay_shards,
                replay_diag=False)
            self.service = ReplayService(
                shard_spec, cfg.fleet.replay_shards,
                spill_blocks=cfg.fleet.spill_blocks,
                route=cfg.fleet.replay_route,
                promote_per_sample=cfg.fleet.spill_promote_per_sample,
                ingest_batch_blocks=cfg.fleet.ingest_batch_blocks,
                spill_prefetch=cfg.fleet.spill_prefetch,
                tier_stats=(cfg.telemetry.enabled
                            and cfg.telemetry.replay_tiers_enabled))
            # experience lineage (ISSUE 19): sampled-batch stamps looked
            # up from the service's ring mirrors feed the record's
            # 'trace' block (env-step->gradient latency)
            if cfg.telemetry.enabled and cfg.telemetry.tracing_enabled:
                from r2d2_tpu.telemetry.tracing import ExperienceTrace
                self._exp_trace = ExperienceTrace(
                    cfg.telemetry.trace_sample_every)
            # service-mode sample staging (ISSUE 16): the PR-2 stager
            # treatment for the consumer side — a prefetch thread draws
            # the next per-shard batch while the train dispatch runs,
            # and priority write-backs batch per sampled shard on a
            # writeback thread (off = the synchronous PR-15 step,
            # byte-identical)
            self._svc_staging = cfg.fleet.sample_staging
            if self._svc_staging:
                self._svc_error: Optional[BaseException] = None
                self._svc_prefetch_q: queue_mod.Queue = queue_mod.Queue(
                    maxsize=2)
                self._svc_writeback_q: queue_mod.Queue = queue_mod.Queue(
                    maxsize=64)
                self._svc_stop = threading.Event()
                self._svc_threads: list = []
            # one service-sampled batch per step — same degradation the
            # host branch warns about, made equally loud here
            if cfg.runtime.steps_per_dispatch > 1:
                import logging
                logging.getLogger(__name__).warning(
                    "fleet.replay_shards: ignoring "
                    "runtime.steps_per_dispatch=%d (the service-routed "
                    "learner trains one service-sampled batch per step)",
                    cfg.runtime.steps_per_dispatch)
            self._k = 1
            self.replay_state = None
            self._step_fn = make_external_batch_step(
                net, shard_spec, cfg.optim, cfg.network.use_double,
                diag=self._diag, rdiag=self._rdiag)
            self._service_key = jax.random.PRNGKey(seed + 777
                                                   + 1000 * player_idx)
        elif self.host_mode:
            # dispatch amortization needs the device-resident replay (each
            # host-mode step consumes one host-sampled batch); degrade
            # rather than reject. Warn only for an explicitly-set value > 1
            # (the -1 auto default resolves silently). (warning, not info:
            # nothing configures logging, so only the stdlib lastResort
            # handler [WARNING+] makes this visible)
            import logging
            if cfg.runtime.steps_per_dispatch > 1:
                logging.getLogger(__name__).warning(
                    "replay.placement='host': ignoring "
                    "runtime.steps_per_dispatch=%d (host mode trains one "
                    "host-sampled batch per step)",
                    cfg.runtime.steps_per_dispatch)
            self._k = 1
            self._bg_error: Optional[BaseException] = None
            self.replay_state = None
            self.host_replay = HostReplay(self.spec, seed=seed)
            if cfg.mesh.mp > 1:
                # tensor parallelism (parallel/tensor_parallel.py): the
                # SAME external-batch step with params feature-sharded
                # over 'mp' and the batch over 'dp' — GSPMD inserts the
                # collectives. place_batch runs in the prefetch thread.
                from r2d2_tpu.parallel import make_mesh
                from r2d2_tpu.parallel.tensor_parallel import (
                    make_tp_external_batch_step)
                tp_mesh = make_mesh(cfg.mesh)
                self._step_fn, place_state, self._place_batch = (
                    make_tp_external_batch_step(
                        net, self.spec, cfg.optim, cfg.network.use_double,
                        tp_mesh, diag=self._diag, rdiag=self._rdiag))
                self.train_state = place_state(self.train_state)
            else:
                self._step_fn = make_external_batch_step(
                    net, self.spec, cfg.optim, cfg.network.use_double,
                    diag=self._diag, rdiag=self._rdiag)
                self._place_batch = jax.device_put
            self._prefetch_q: queue_mod.Queue = queue_mod.Queue(
                maxsize=max(1, cfg.runtime.prefetch_batches))
            self._writeback_q: queue_mod.Queue = queue_mod.Queue(maxsize=64)
            self._bg_stop = threading.Event()
            self._bg_threads: list = []
        else:
            dp = cfg.mesh.resolved_dp(len(jax.devices()))
            self._k = cfg.runtime.resolved_steps_per_dispatch()
            if dp > 1 or cfg.mesh.mp > 1:
                # dp-sharded learner (SURVEY §5.8): replay sharded
                # chip-per-shard, per-shard prioritized sampling, gradient
                # pmean over ICI. Blocks round-robin across shards.
                # mp > 1 composes: the same fused step runs manual over dp
                # and GSPMD-auto over mp, with the TrainState's wide
                # feature dims sharded over mp (tensor_parallel) and replay
                # mp-replicated — model sharding stays a mesh-axis change
                # on the device-replay flagship path (VERDICT r3 #4).
                from r2d2_tpu.parallel import (
                    make_mesh, make_sharded_learner_step,
                    make_sharded_replay_add, sharded_replay_init)
                self.mesh = make_mesh(cfg.mesh)
                self._dp = self.mesh.shape["dp"]
                self._next_shard = 0
                if cfg.mesh.mp > 1:
                    from r2d2_tpu.parallel.tensor_parallel import (
                        state_shardings)
                    self.train_state = jax.device_put(
                        self.train_state,
                        state_shardings(self.train_state, self.mesh))
                self.replay_state = sharded_replay_init(self.spec, self.mesh)
                self._step_fn = make_sharded_learner_step(
                    net, self.spec, cfg.optim, cfg.network.use_double,
                    self.mesh, steps_per_dispatch=self._k, diag=self._diag,
                    rdiag=self._rdiag)
                self._sharded_add = make_sharded_replay_add(
                    self.spec, self.mesh)
            else:
                self.replay_state = replay_init(self.spec)
                if self._k > 1:
                    self._step_fn = make_multi_learner_step(
                        net, self.spec, cfg.optim, cfg.network.use_double,
                        self._k, diag=self._diag, rdiag=self._rdiag)
                else:
                    self._step_fn = make_learner_step(
                        net, self.spec, cfg.optim, cfg.network.use_double,
                        diag=self._diag, rdiag=self._rdiag)

        self.metrics = metrics or TrainMetrics(player_idx, cfg.runtime.save_dir,
                                               resume=bool(cfg.runtime.resume))
        if self._exp_trace is not None:
            # experience lineage (ISSUE 19): the record's 'trace' block
            self.metrics.set_tracing(self._exp_trace.interval_block)
        self.publish: Optional[Callable] = None   # wired by orchestrator

        # Ring accounting: ONE RingAccountant per replay (VERDICT r2 weak
        # #5). Host placement shares HostReplay's own instance; device
        # placement keeps a host mirror of the compiled pointer in
        # ReplayState.block_ptr — mirroring avoids a blocking device read (a
        # full tunnel round-trip under remote TPU dispatch) per ingested
        # block, and replay_add advances the device pointer with the
        # identical wrap rule (asserted in tests/test_replay.py).
        from r2d2_tpu.replay.structs import RingAccountant
        if self.service is not None:
            # the service IS the accounting facade: per-shard
            # RingAccountants advance inside add_block, and the facade's
            # buffer_steps/total_adds/live_versions sum them — the same
            # duck-typed surface the gate/metrics/flush read
            self.ring = self.service
        elif self.host_mode:
            self.ring = self.host_replay.ring
        else:
            # round-robin feeding visits the dp shards' ring slots in a
            # single global order — one accountant over dp * num_blocks
            # slots mirrors every shard's compiled pointer exactly
            self.ring = RingAccountant(
                self.spec.num_blocks * (self._dp if self.mesh else 1))
        self.env_steps = resumed_env_steps
        self._host_step = int(self.train_state.step)
        # last step a checkpoint covered: save_final() is a no-op unless
        # training advanced past it (nothing new to save at construction,
        # resumed or fresh)
        self._last_saved_step = self._host_step
        # Rate-limiter baselines: the collect:learn budget is measured from
        # THIS process's starting point, not from step/env-step zero — a
        # resumed run restores large cumulative counters while its replay
        # ring restarts empty, and an absolute comparison would pause
        # ingestion forever (training could never start).
        self._ratio_env_base = self.env_steps
        self._ratio_step_base = self._host_step
        self._pending_losses: list = []   # device scalars, flushed lazily

        # -- batched + pipelined ingestion (ISSUE 2) --
        # K > 1 (device placement only): a background stager thread drains
        # the feeder queue in stacked K-block batches and launches their
        # host→device transfer while the current train dispatch runs; the
        # main thread commits staged batches (ONE replay_add_many dispatch
        # per batch) between train dispatches, where ring/rate-limiter
        # accounting happens — the same interleaving point the per-block
        # path uses, so the fused step's priority write-back stays
        # race-free. Host placement keeps K = 1: its ingest is a numpy
        # copy, not a device dispatch.
        # service mode keeps the per-block drain (K = 1): spill
        # retention shadows each block's host page at add time, and the
        # service's routing is per-block by definition
        self._ingest_k = (1 if (self.host_mode or self.service is not None)
                          else
                          min(cfg.replay.resolved_ingest_batch_blocks(),
                              self.spec.num_blocks))
        self._sharded_add_many = None
        if self.mesh is not None and self._ingest_k > 1:
            from r2d2_tpu.parallel import make_sharded_replay_add_many
            self._sharded_add_many = make_sharded_replay_add_many(
                self.spec, self.mesh)
        self._stager: Optional[threading.Thread] = None
        # AOT add_many executables per batch size, compiled in the STAGER
        # thread before a batch is enqueued, so a new batch size never
        # stalls the commit path with an XLA compile — on the dp-sharded
        # path too (batched ingestion auto-engages on TPU, where a lazy
        # ~1.5 s mid-run compile measurably parks the actors). Replay
        # shape/sharding avals are captured now, before any donation
        # invalidates the live arrays.
        self._add_many_cache: dict = {}
        if self.replay_state is None:
            self._replay_shapes = None
        elif self.mesh is not None:
            # sharding-annotated avals: lowering a shard_map program from
            # plain ShapeDtypeStructs would let the compiler pick layouts
            # the committed per-shard arrays then fail to match
            self._replay_shapes = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                               sharding=x.sharding),
                self.replay_state)
        else:
            self._replay_shapes = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
                self.replay_state)
        self._ingest_stop = threading.Event()
        # buffer attribution (ISSUE 7): register this player's device
        # footprints with the process registry so the periodic record's
        # resources block names owners instead of one opaque HBM total.
        # Names are re-registered on a rebuilt Learner (same-name
        # overwrite), and registration is read-side-only — with
        # resources off nothing ever reads it, so the record schema
        # stays byte-identical.
        if cfg.telemetry.enabled and cfg.telemetry.resources_enabled:
            from r2d2_tpu.telemetry.resources import (clear_player_buffers,
                                                      pytree_nbytes,
                                                      register_buffer)
            # drop the previous incarnation's entries first: same-name
            # overwrite doesn't cover components the rebuilt stack
            # LACKS (e.g. an earlier run's stager staging window when
            # this run drains per-block)
            clear_player_buffers(player_idx)
            register_buffer(f"p{player_idx}/train_state",
                            pytree_nbytes(self.train_state))
            if self.replay_state is not None:
                register_buffer(f"p{player_idx}/replay_ring",
                                pytree_nbytes(self.replay_state))
            if self.service is not None:
                register_buffer(f"p{player_idx}/replay_service",
                                self.service.device_bytes)
        # depth 2: one batch committing + one transfer in flight bounds
        # staged memory at 2K blocks while keeping the pipeline full
        self._ingest_q: queue_mod.Queue = queue_mod.Queue(maxsize=2)
        # one-shot 'costs' record block (ISSUE 9), latched at first flush
        self._costs_attached = False
        self._ingest_error: Optional[BaseException] = None
        self._staged_env_steps = 0        # popped but not yet committed
        self._staged_blocks = 0
        self._staged_lock = threading.Lock()
        self._pause_started: Optional[float] = None

        # -- crash-recovery plane (ISSUE 18) --
        # runtime.snapshot_interval > 0: a background SnapshotWriter
        # persists a consistent cut of the replay plane (service shards
        # or the in-mesh state) at interval boundaries; on resume with
        # runtime.restore_replay the newest committed cut is loaded back
        # bit-exactly BEFORE training continues.
        self._snap_writer = None
        self._restores = 0
        self._restored_blocks = 0
        self._snap_capture_s = 0.0
        # adds committed at the last snapshot — lost_blocks_est is the
        # gauge of what a crash RIGHT NOW would cost (bounded by the
        # snapshot interval; the kill drill measures it for real)
        self._snap_adds = 0
        if cfg.runtime.snapshot_interval > 0 and not self.host_mode:
            from r2d2_tpu.replay.snapshot import SnapshotWriter
            self._snap_writer = SnapshotWriter(cfg.runtime.save_dir,
                                               player_idx)
        if (cfg.runtime.resume and cfg.runtime.restore_replay
                and not self.host_mode):
            self._restore_replay_snapshot()

    def _restore_replay_snapshot(self) -> None:
        """Resume plane c (ISSUE 18): reload the newest committed replay
        snapshot next to the checkpoint — every shard's ring/tree/stamps
        /spill pages plus the service sample key, so the restored
        learner's next sample (and next-step loss) equals the
        uninterrupted twin's. Silently a no-op when no snapshot exists
        (a pre-PR18 resume restores params/opt-state only)."""
        from r2d2_tpu.replay.snapshot import load_snapshot, restore_plain
        snap = load_snapshot(self.cfg.runtime.save_dir, self.player_idx)
        if snap is None:
            return
        if self.service is not None:
            self.service.restore_state(snap)
            key = snap["extra"].get("service_key")
            if key is not None:
                self._service_key = jax.device_put(
                    np.asarray(key, np.uint32))
        else:
            self.replay_state = restore_plain(
                self.spec, self.replay_state, self.ring, snap)
            if self.mesh is not None:
                self._next_shard = int(
                    snap["extra"].get("next_shard", 0))
            key = snap["extra"].get("train_key")
            if key is not None:
                cur = self.train_state.key
                self.train_state = self.train_state.replace(
                    key=jax.device_put(np.asarray(key, np.uint32),
                                       cur.sharding))
        self._restores = 1
        self._restored_blocks = sum(s["ring"]["total_adds"]
                                    for s in snap["shards"])
        self._snap_adds = self.ring.total_adds
        env_steps = snap["extra"].get("env_steps")
        if env_steps is not None:
            # the checkpoint's env_steps counter stopped at its save;
            # the snapshot's cut is newer (or equal) — adopt the later
            self.env_steps = max(self.env_steps, int(env_steps))
        self.metrics.set_buffer_size(self.ring.buffer_steps)

    @property
    def tele(self):
        """The process Telemetry, read through metrics DYNAMICALLY: the
        orchestrator attaches it to TrainMetrics (set_telemetry), possibly
        after this Learner was constructed; a stale binding here would
        silently observe into the NULL sink forever."""
        return self.metrics.telemetry

    # -- ingestion --

    def ingest(self, block: Block) -> None:
        """Ring-write of one actor block (ref worker.py:85-120) — jitted on
        device, or into the host replay. Accounting goes through the single
        RingAccountant so the device path never blocks on a pointer read."""
        learning = int(np.asarray(block.learning_steps).sum())
        if self.host_mode:
            self.host_replay.add(block)   # advances the shared accountant
        elif self.service is not None:
            # routed by shard key; the per-shard accountants (and the
            # spill-tier demotion of whatever the ring-write overwrote)
            # advance inside the service
            self.service.add_block(block)
        else:
            # strip the lineage leaf before the jitted add (the in-mesh
            # programs are compiled traceless — the service path's AOT
            # discipline); the stamp lands in the accountant mirror
            trace = block.trace_ms
            if trace is not None:
                trace = int(np.asarray(trace))
                block = block.replace(trace_ms=None)
            if self.mesh is not None:
                self.replay_state = self._sharded_add(
                    self.replay_state, block, self._next_shard)
                self._next_shard = (self._next_shard + 1) % self._dp
            else:
                self.replay_state = replay_add(
                    self.spec, self.replay_state, block)
            wv = int(np.asarray(block.weight_version))
            if trace is None:
                self.ring.advance(learning, wv)
            else:
                from r2d2_tpu.telemetry.tracing import now_ms
                self.ring.advance(learning, wv, trace_ms=trace,
                                  ingest_ms=(now_ms() if trace >= 0
                                             else -1))
        self.env_steps += learning
        ret = float(np.asarray(block.sum_reward))
        self.metrics.on_block(learning, None if np.isnan(ret) else ret)
        self.metrics.set_buffer_size(self.ring.buffer_steps)

    @property
    def ingestion_paused(self) -> bool:
        """Rate limiter (replay.max_env_steps_per_train_step): true when
        data collection is far enough ahead of learning that ingestion
        should wait. Leaving blocks in the bounded feeder queue
        back-pressures the actors (they park in put()), pinning the
        collect:learn ratio independently of host scheduling."""
        ratio = self.cfg.replay.max_env_steps_per_train_step
        if ratio <= 0:
            return False
        # Staged-but-uncommitted blocks count as collected EVERYWHERE in
        # this check: they were already popped from the feeder and WILL
        # commit at the next drain regardless of training, so (a) counting
        # them toward the training-gate fill cannot livelock, and (b) NOT
        # counting them would let the stager pull far past the budget
        # while commits lag behind pops (the gate would read open forever).
        with self._staged_lock:
            staged_steps = self._staged_env_steps
            staged_blocks = self._staged_blocks
        # Never pause while the training gate is closed: ingestion is the
        # only thing that can open it (learning_starts fill, and under a dp
        # mesh one block per shard), so pausing there would livelock —
        # drain() returns 0 forever while ready waits for a block that can
        # never arrive.
        if not self._gate_open(staged_blocks, staged_steps):
            return False
        budget = (self.cfg.replay.learning_starts
                  + ratio * max(self._host_step - self._ratio_step_base, 1))
        return (self.env_steps + staged_steps
                - self._ratio_env_base) >= budget

    def _note_pause(self, paused: bool) -> None:
        """Rate-limiter pause-time accounting (whichever thread owns the
        feeder-pop loop calls this: the main thread on the legacy path, the
        stager on the pipelined path)."""
        if paused:
            if self._pause_started is None:
                self._pause_started = time.time()
        elif self._pause_started is not None:
            self.metrics.on_ingest_pause(time.time() - self._pause_started)
            self._pause_started = None

    def drain(self, queue, max_items: Optional[int] = None) -> int:
        """Move actor blocks from the feeder queue into the replay. Legacy
        path (ingest_batch_blocks = 1): pop + ingest synchronously, up to
        ``max_items`` (default replay.drain_max_blocks — one knob for this
        loop and the orchestrator's warm-up loop). Pipelined path (K > 1):
        commit whatever stacked batches the stager has staged; the stager
        drains the feeder in K-block bursts on its own thread."""
        if self._ingest_k > 1:
            return self._drain_pipelined(queue)
        if max_items is None:
            max_items = self.cfg.replay.drain_max_blocks
        paused = self.ingestion_paused
        self._note_pause(paused)
        if paused:
            return 0
        t0 = time.time()
        blocks = queue.drain(max_items)
        t_get = time.time()
        if (self.service is not None and self.service.ingest_k > 1
                and len(blocks) > 1):
            # grouped service ingest (ISSUE 16): one routed add_blocks
            # call commits the whole drain through per-shard
            # replay_add_many chunks — bit-identical contents, one
            # dispatch per chunk instead of per block. The
            # orchestrator's warm-up loop reaches this through the same
            # drain(), so bring-up bursts get the grouped plane too.
            self._ingest_group(blocks)
        else:
            for blk in blocks:
                self.ingest(blk)
        if self.service is not None and self.service.ingest_k > 1:
            # producer-side depth left behind this drain — the
            # ingest_backlog alert's gauge (qsize -1 = unknown -> 0)
            self.service.note_backlog(queue.qsize())
        if blocks:
            t1 = time.time()
            self.metrics.on_ingest_drain(len(blocks), t1 - t0)
            tele = self.tele
            tele.observe("ingest/ring_get", t_get - t0)
            tele.observe("ingest/commit", t1 - t_get)
            tele.record_span("ingest/commit", t0, t1,
                             {"blocks": len(blocks)})
        return len(blocks)

    def _ingest_group(self, blocks: List[Block]) -> None:
        """Grouped service commit with the same per-block accounting the
        sequential :meth:`ingest` loop performs (env steps, episode
        returns, buffer gauge) — the ring facade's totals advance inside
        the service exactly as K sequential adds would."""
        self.service.add_blocks(blocks)
        for block in blocks:
            learning = int(np.asarray(block.learning_steps).sum())
            self.env_steps += learning
            ret = float(np.asarray(block.sum_reward))
            self.metrics.on_block(learning, None if np.isnan(ret) else ret)
        self.metrics.set_buffer_size(self.ring.buffer_steps)

    # -- pipelined ingestion (stager thread + commit) --

    def _drain_pipelined(self, queue) -> int:
        if self._ingest_error is not None:
            raise RuntimeError(
                "ingest stager thread died") from self._ingest_error
        if self._stager is None or not self._stager.is_alive():
            self._start_stager(queue)
        committed = 0
        # same per-drain block cap as the legacy path: a producer that
        # outpaces the learner must not starve the train loop by keeping
        # this commit loop spinning
        while committed < self.cfg.replay.drain_max_blocks:
            try:
                staged, metas, t_pop = self._ingest_q.get_nowait()
            except queue_mod.Empty:
                break
            committed += self._commit_staged(staged, metas, t_pop)
        self.metrics.set_ingest_queue_depth(self._ingest_q.qsize())
        return committed

    def _commit_staged(self, staged: Block, metas, t_pop: float) -> int:
        """ONE device dispatch ring-writes the whole stacked batch; ring
        pointer, rate-limiter env-step base, and metrics account here — at
        commit time, on the main thread — so back-pressure and the
        device/host pointer mirror keep the per-block path's semantics."""
        k = len(metas)
        t_commit = time.time()
        # the stager AOT-compiled this batch size before enqueueing
        exe = self._add_many_cache.get(k)
        if self.mesh is not None:
            if exe is not None:
                self.replay_state = exe(self.replay_state, staged,
                                        np.int32(self._next_shard))
            else:   # defensive fallback: jit-call path (compiles here)
                self.replay_state = self._sharded_add_many(
                    self.replay_state, staged, self._next_shard)
            self._next_shard = (self._next_shard + k) % self._dp
        else:
            if exe is not None:
                self.replay_state = exe(self.replay_state, staged)
            else:
                self.replay_state = replay_add_many(
                    self.spec, self.replay_state, staged)
        total = 0
        for learning, ret, wv, trace in metas:
            if trace is None:
                self.ring.advance(learning, wv)
            else:
                from r2d2_tpu.telemetry.tracing import now_ms
                self.ring.advance(learning, wv, trace_ms=trace,
                                  ingest_ms=(now_ms() if trace >= 0
                                             else -1))
            self.metrics.on_block(learning, ret)
            total += learning
        self.env_steps += total
        with self._staged_lock:
            self._staged_env_steps -= total
            self._staged_blocks -= k
        self.metrics.set_buffer_size(self.ring.buffer_steps)
        now = time.time()
        self.metrics.on_ingest_drain(k, now - t_pop)
        self.tele.observe("ingest/commit", now - t_commit)
        self.tele.record_span("ingest/commit", t_commit, now, {"blocks": k})
        return k

    def _compile_add_many(self, kb: int):
        """Lower + AOT-compile the add_many executable for batch size
        ``kb`` — the ONE lowering recipe (stager thread only), shared by
        the startup precompile and the odd-size fallback, deriving block
        avals from the authoritative record layout (empty_block_np)."""
        from r2d2_tpu.replay.structs import empty_block_np
        proto = empty_block_np(self.spec)
        blocks = Block(**{
            name: jax.ShapeDtypeStruct((kb,) + arr.shape, arr.dtype)
            for name, arr in proto.items()})
        if self.mesh is not None:
            shard = jax.ShapeDtypeStruct((), np.int32)
            return self._sharded_add_many.lower(
                self._replay_shapes, blocks, shard).compile()
        return replay_add_many.lower(
            self.spec, self._replay_shapes, blocks).compile()

    def _aot_bucket_sizes(self) -> list:
        """The add_many batch sizes the stager drains — every power-of-two
        bucket up to K PLUS K itself: a non-pow2 ingest_batch_blocks is
        the steady-state drain size under load and would otherwise hit
        the lazy mid-run compile exactly when load first reaches K. One
        recipe shared by the startup precompile and the coverage report
        (telemetry/compile.py), so the report can never drift from what
        the precompile actually targets."""
        sizes = []
        kb = 1
        while kb < self._ingest_k:
            sizes.append(kb)
            kb *= 2
        sizes.append(self._ingest_k)
        return sizes

    def aot_coverage(self) -> Optional[dict]:
        """AOT-precompile coverage of the stager's add_many buckets
        (ISSUE 7): expected bucket sizes vs actually-compiled executables
        — a non-empty ``missing`` list means a mid-run lazy compile is
        still possible, the exact hazard the precompile exists to
        prevent. None on the legacy per-block path (no stager)."""
        if self._ingest_k <= 1:
            return None
        from r2d2_tpu.telemetry.compile import aot_coverage
        return aot_coverage(self._aot_bucket_sizes(),
                            list(self._add_many_cache))

    def _precompile_add_many(self) -> None:
        """AOT-compile add_many for every stager bucket size — runs once
        in the stager thread at startup, i.e. during the warm-up fill, so
        a ~1.5 s XLA compile never stalls mid-run ingestion (measured: a
        lazy mid-run compile backs the feeder up enough to park the
        actors)."""
        for kb in self._aot_bucket_sizes():
            if self._ingest_stop.is_set():
                break
            if kb not in self._add_many_cache:
                self._add_many_cache[kb] = self._compile_add_many(kb)

    def _start_stager(self, queue) -> None:
        cfg = self.cfg
        if cfg.telemetry.enabled and cfg.telemetry.resources_enabled:
            # staging-window attribution (ISSUE 7): the pipeline holds at
            # most 2 staged batches of K blocks (queue depth 2) — the
            # bound, not a live gauge; registered once at stager start
            from r2d2_tpu.replay.structs import empty_block_np
            from r2d2_tpu.telemetry.resources import register_buffer
            block_bytes = sum(a.nbytes
                              for a in empty_block_np(self.spec).values())
            register_buffer(f"p{self.player_idx}/ingest_staging",
                            2 * self._ingest_k * block_bytes)

        def stage_loop():
            try:
                self._precompile_add_many()
                while not self._ingest_stop.is_set():
                    paused = self.ingestion_paused
                    self._note_pause(paused)
                    if paused:
                        time.sleep(0.002)
                        continue
                    t_pop = time.time()
                    # Drain what is queued NOW, rounded down to a power-of-
                    # two bucket (bounds the distinct compiled add_many
                    # batch sizes at log2(K)+1) — never wait for a full
                    # batch: an explicit accumulation window throttles
                    # ingestion below the offered load and back-pressures
                    # the actors for nothing. Batching emerges under load
                    # on its own — while the bounded staging queue is full,
                    # the feeder accumulates and the next drain sees a
                    # bigger bucket.
                    want = self._ingest_k
                    avail = queue.qsize()
                    if avail == 0:
                        time.sleep(0.001)
                        continue
                    if 0 < avail < want:
                        want = 1 << (avail.bit_length() - 1)
                    stacked, k = queue.drain_stacked(want)
                    if k == 0:
                        time.sleep(0.001)
                        continue
                    self.tele.observe("ingest/ring_get",
                                      time.time() - t_pop)
                    if k not in self._add_many_cache:
                        # odd size (qsize-less backend): compile HERE
                        # (stager thread), never at commit
                        self._add_many_cache[k] = self._compile_add_many(k)
                    trace = stacked.trace_ms
                    if trace is not None:
                        # strip before staging — the AOT add_many avals
                        # are traceless (the per-block path's discipline);
                        # stamps mirror into the accountant at commit
                        trace = np.asarray(trace, np.int64)
                        stacked = stacked.replace(trace_ms=None)
                    learning = np.asarray(stacked.learning_steps)\
                        .sum(axis=1).astype(np.int64)
                    rets = np.asarray(stacked.sum_reward, np.float32)
                    wvs = np.asarray(stacked.weight_version, np.int64)
                    metas = [
                        (int(learning[i]),
                         None if np.isnan(rets[i]) else float(rets[i]),
                         int(wvs[i]),
                         int(trace[i]) if trace is not None else None)
                        for i in range(k)]
                    with self._staged_lock:
                        self._staged_env_steps += int(learning.sum())
                        self._staged_blocks += k
                    # starts the host→device transfer; it proceeds while
                    # the main thread's train dispatch runs (replicated
                    # across the mesh on the dp-sharded path, matching the
                    # AOT executable's P() block avals)
                    if self.mesh is not None:
                        from jax.sharding import (
                            NamedSharding, PartitionSpec)
                        staged = jax.device_put(
                            stacked, NamedSharding(self.mesh,
                                                   PartitionSpec()))
                    else:
                        staged = jax.device_put(stacked)
                    now = time.time()
                    # stage = pop + stack + host->device launch; the wait
                    # for a staging-queue slot below is back-pressure, not
                    # staging work, and stays out of the histogram
                    self.tele.observe("ingest/stage", now - t_pop)
                    self.tele.record_span("ingest/stage", t_pop, now,
                                          {"blocks": k})
                    while not self._ingest_stop.is_set():
                        try:
                            self._ingest_q.put((staged, metas, t_pop),
                                               timeout=0.2)
                            break
                        except queue_mod.Full:
                            continue
            except BaseException as e:   # surfaced by _drain_pipelined
                self._ingest_error = e
                raise

        self._stager = threading.Thread(
            target=stage_loop, daemon=True,
            name=f"learner-ingest-stager-p{self.player_idx}")
        self._stager.start()

    def _gate_open(self, extra_blocks: int = 0, extra_steps: int = 0) -> bool:
        """The training-gate conditions — ONE implementation shared by
        ``ready`` (committed blocks only) and the rate limiter's pause
        check (committed + staged), so the two cannot drift apart and
        re-open the pause-before-ready livelock."""
        if (self.mesh is not None
                and self.ring.total_adds + extra_blocks < self._dp):
            return False
        if self.service is not None and not self.service.all_shards_nonempty:
            # every service shard must hold a block before sampling (an
            # empty tree yields NaN importance weights — the dp mesh's
            # same precondition, enforced per addressable shard)
            return False
        return (self.ring.buffer_steps + extra_steps
                >= self.cfg.replay.learning_starts)

    @property
    def ready(self) -> bool:
        """Training gate (ref worker.py:214-218, config.learning_starts).
        Under a dp mesh every shard must also hold at least one block —
        per-shard prioritized sampling over an empty tree yields NaN
        importance weights."""
        return self._gate_open()

    @property
    def training_steps(self) -> int:
        """Host-mirrored step counter (no device sync)."""
        return self._host_step

    # -- host-placement pipeline (ref worker.py:292-306,368) --

    def _start_background(self) -> None:
        def prefetch():
            try:
                while not self._bg_stop.is_set():
                    t0 = time.time()
                    batch, snapshot = self.host_replay.sample()
                    dev = self._place_batch(batch)
                    self.tele.observe("learner/sample", time.time() - t0)
                    while not self._bg_stop.is_set():
                        try:
                            self._prefetch_q.put((dev, snapshot), timeout=0.5)
                            break
                        except queue_mod.Full:
                            continue
            except BaseException as e:  # surfaced by _host_step_once
                self._bg_error = e
                raise

        def writeback():
            try:
                while not self._bg_stop.is_set():
                    try:
                        idxes, prios, snapshot = self._writeback_q.get(timeout=0.5)
                    except queue_mod.Empty:
                        continue
                    t0 = time.time()
                    self.host_replay.update_priorities(
                        np.asarray(idxes), np.asarray(jax.device_get(prios)),
                        snapshot)
                    self.tele.observe("learner/priority_writeback",
                                      time.time() - t0)
            except BaseException as e:
                self._bg_error = e
                raise

        for fn, name in ((prefetch, "prefetch"), (writeback, "prio-writeback")):
            t = threading.Thread(target=fn, daemon=True,
                                 name=f"learner-{name}-p{self.player_idx}")
            t.start()
            self._bg_threads.append(t)

    def stop_background(self, join_timeout: float = 10.0) -> None:
        stuck = []
        if self._snap_writer is not None:
            # drain + stop the snapshot writer first: a queued cut still
            # writing must land (it is newer than anything on disk)
            self._snap_writer.stop(join_timeout)
        if self._stager is not None:
            # drain the staging queue so a stager parked in a full-queue
            # put can observe the stop event; staged-but-uncommitted
            # blocks are dropped (shutdown only)
            self._ingest_stop.set()
            deadline = time.time() + join_timeout
            while self._stager.is_alive() and time.time() < deadline:
                try:
                    self._ingest_q.get_nowait()
                except queue_mod.Empty:
                    pass
                self._stager.join(timeout=0.1)
            if self._stager.is_alive():
                stuck.append(self._stager.name)
            else:
                self._stager = None
        if self.service is not None:
            # service stager threads (ISSUE 16 sample staging) + the
            # service's own prefetch thread; both no-ops when off
            if self._svc_staging and self._svc_threads:
                self._svc_stop.set()
                for t in self._svc_threads:
                    deadline = time.time() + join_timeout
                    while t.is_alive() and time.time() < deadline:
                        try:
                            self._svc_prefetch_q.get_nowait()
                        except queue_mod.Empty:
                            pass
                        t.join(timeout=0.1)
                    if t.is_alive():
                        stuck.append(t.name)
                self._svc_threads = [t for t in self._svc_threads
                                     if t.is_alive()]
            self.service.close()
        if not self.host_mode:
            if stuck:
                import logging
                logging.getLogger(__name__).warning(
                    "learner background threads did not exit within %.1fs: "
                    "%s", join_timeout, stuck)
            return
        self._bg_stop.set()
        # Unblock a prefetch thread parked in a full-queue put by draining
        # the prefetch queue, then join; surface anything still stuck (a
        # thread blocked inside a device transfer would otherwise outlive
        # the orchestrator's close() silently).
        for t in self._bg_threads:
            deadline = time.time() + join_timeout
            while t.is_alive() and time.time() < deadline:
                try:
                    self._prefetch_q.get_nowait()
                except queue_mod.Empty:
                    pass
                t.join(timeout=0.1)
            if t.is_alive():
                stuck.append(t.name)
        self._bg_threads = [t for t in self._bg_threads if t.is_alive()]
        if stuck:
            import logging
            logging.getLogger(__name__).warning(
                "learner background threads did not exit within %.1fs: %s",
                join_timeout, stuck)

    def _host_step_once(self) -> dict:
        if not self._bg_threads:
            self._start_background()
        while True:
            try:
                batch, snapshot = self._prefetch_q.get(timeout=2.0)
                break
            except queue_mod.Empty:
                # fail loudly instead of hanging if a pipeline thread died
                if self._bg_error is not None:
                    raise RuntimeError(
                        "host-replay pipeline thread died"
                    ) from self._bg_error
                if not any(t.is_alive() for t in self._bg_threads):
                    raise RuntimeError(
                        "host-replay pipeline threads exited without error")
        self.train_state, m = self._step_fn(self.train_state, batch)
        # async priority write-back (ref worker.py:368); staleness-guarded
        try:
            self._writeback_q.put_nowait(
                (batch.idxes, m.pop("priorities"), snapshot))
        except queue_mod.Full:
            m.pop("priorities", None)   # drop under backpressure — counted
            self.metrics.on_dropped_priority_update()
        return m

    # -- service-mode step (ISSUE 15; ISSUE 16 sample staging) --

    def _start_service_stager(self) -> None:
        """fleet.sample_staging: the host-placement pipeline's shape on
        the service path — a prefetch thread draws the next prioritized
        batch (service.sample is already device-resident, so staging
        hides the sample/promotion latency, not a transfer) and a
        writeback thread applies priority updates grouped per sampled
        shard (one lock acquisition per group via
        service.update_priorities_group; each entry keeps its own
        adds-snapshot staleness guard)."""
        def prefetch():
            try:
                while not self._svc_stop.is_set():
                    self._service_key, key = jax.random.split(
                        self._service_key)
                    t0 = time.time()
                    batch, shard, snapshot = self.service.sample(key)
                    self.tele.observe("learner/sample", time.time() - t0)
                    token = None
                    if self._exp_trace is not None:
                        token = self._exp_trace.on_sample(
                            self.service.trace_lookup(
                                shard, np.asarray(batch.idxes)))
                    staged = (batch, shard, snapshot, token)
                    while not self._svc_stop.is_set():
                        try:
                            self._svc_prefetch_q.put(staged, timeout=0.5)
                            break
                        except queue_mod.Full:
                            continue
            except BaseException as e:  # surfaced by _service_step_staged
                self._svc_error = e
                raise

        def writeback():
            try:
                while not self._svc_stop.is_set():
                    try:
                        first = self._svc_writeback_q.get(timeout=0.5)
                    except queue_mod.Empty:
                        continue
                    entries = [first]
                    while True:     # batch whatever is immediately ready
                        try:
                            entries.append(self._svc_writeback_q.get_nowait())
                        except queue_mod.Empty:
                            break
                    groups: dict = {}
                    for shard, idxes, prios, snapshot in entries:
                        groups.setdefault(shard, []).append(
                            (np.asarray(idxes),
                             np.asarray(jax.device_get(prios)), snapshot))
                    t0 = time.time()
                    for shard, group in groups.items():
                        self.service.update_priorities_group(shard, group)
                    self.tele.observe("learner/priority_writeback",
                                      time.time() - t0)
            except BaseException as e:
                self._svc_error = e
                raise

        for fn, name in ((prefetch, "svc-prefetch"),
                         (writeback, "svc-writeback")):
            t = threading.Thread(target=fn, daemon=True,
                                 name=f"learner-{name}-p{self.player_idx}")
            t.start()
            self._svc_threads.append(t)

    def _service_step_staged(self) -> dict:
        if not self._svc_threads:
            self._start_service_stager()
        while True:
            try:
                batch, shard, snapshot, token = self._svc_prefetch_q.get(
                    timeout=2.0)
                break
            except queue_mod.Empty:
                # fail loudly instead of hanging if a stager thread died
                if self._svc_error is not None:
                    raise RuntimeError(
                        "service stager thread died") from self._svc_error
                if not any(t.is_alive() for t in self._svc_threads):
                    raise RuntimeError(
                        "service stager threads exited without error")
        self.train_state, m = self._step_fn(self.train_state, batch)
        if self._exp_trace is not None:
            self._exp_trace.on_train(token)
        try:
            self._svc_writeback_q.put_nowait(
                (shard, batch.idxes, m.pop("priorities"), snapshot))
        except queue_mod.Full:
            m.pop("priorities", None)   # drop under backpressure — counted
            self.metrics.on_dropped_priority_update()
        return m

    def _service_step_once(self) -> dict:
        """Disaggregated consumer loop: draw one prioritized batch from
        the service's next shard, train through the external-batch step,
        write the new priorities straight back to that shard. In-proc
        producers never interleave an add here (the single-threaded
        drain/step cadence is the same interleaving point the fused
        path relies on); SOCKET producers can, so the write-back rides
        the sample's adds-snapshot through the service's staleness
        guard (a raced batch's update is dropped and counted, never
        written onto the overwriting block). Spill promotion happens
        inside service.sample BEFORE the tree descent, keeping the
        returned idxes valid for this write-back."""
        if self._svc_staging:
            return self._service_step_staged()
        self._service_key, key = jax.random.split(self._service_key)
        t0 = time.time()
        batch, shard, snapshot = self.service.sample(key)
        self.tele.observe("learner/sample", time.time() - t0)
        token = None
        if self._exp_trace is not None:
            token = self._exp_trace.on_sample(
                self.service.trace_lookup(shard, np.asarray(batch.idxes)))
        self.train_state, m = self._step_fn(self.train_state, batch)
        if self._exp_trace is not None:
            self._exp_trace.on_train(token)
        t0 = time.time()
        # the snapshot arms the staleness guard: with socket producers
        # feeding the service concurrently, an add landing mid-step must
        # not have its fresh block's priorities clobbered by this batch
        self.service.update_priorities(shard, batch.idxes,
                                       m.pop("priorities"),
                                       adds_snapshot=snapshot)
        self.tele.observe("learner/priority_writeback", time.time() - t0)
        return m

    # -- training --

    def step(self) -> dict:
        """One device dispatch = ``steps_per_dispatch`` fused steps. Never
        blocks on the device: metrics stay device arrays until
        flush_metrics() (called at log time); the step counter is
        host-mirrored. Publish/checkpoint fire when their interval boundary
        falls inside the dispatched step range."""
        prev = self._host_step
        t0 = time.time()
        if self.host_mode:
            m = self._host_step_once()
        elif self.service is not None:
            m = self._service_step_once()
        else:
            self.train_state, self.replay_state, m = self._step_fn(
                self.train_state, self.replay_state)
        t1 = time.time()
        tele = self.tele
        # host-side dispatch cost (the device executes asynchronously;
        # device occupancy is what xprof captures measure)
        tele.observe("learner/train_dispatch", t1 - t0)
        tele.record_span("learner/train_dispatch", t0, t1,
                         {"k": self._k, "step": prev})
        self._host_step += self._k
        step = self._host_step
        self._pending_losses.append(m["loss"])  # scalar (k=1) or (k,) array
        if self._learning_agg is not None:
            # hold the dispatch's ld/ outputs (device values, no sync);
            # aggregated into the 'learning' record block at flush time
            self._learning_agg.on_dispatch(m)
        if self._replay_agg is not None:
            # same contract for the rd/ outputs (replay pillar, ISSUE 10)
            self._replay_agg.on_dispatch(m)

        rt = self.cfg.runtime
        if (self.publish is not None
                and step // rt.weight_publish_interval
                    > prev // rt.weight_publish_interval):
            t0 = time.time()
            self.publish(self.train_state.params)
            tele.observe("weights/publish", time.time() - t0)
        if rt.save_interval and step // rt.save_interval > prev // rt.save_interval:
            self.save(step // rt.save_interval)
        if (self._snap_writer is not None and rt.snapshot_interval
                and step // rt.snapshot_interval
                    > prev // rt.snapshot_interval):
            self.snapshot_replay()
        return m

    def _capture_replay(self) -> dict:
        """Consistent cut at the commit boundary between dispatches (the
        caller's position in the step loop IS the quiescent point; the
        service capture additionally holds the service lock against
        socket producers and stager threads)."""
        step = self._host_step
        if self.service is not None:
            extra = {
                "service_key": np.asarray(
                    jax.device_get(self._service_key)).tolist(),
                "env_steps": int(self.env_steps),
            }
            return self.service.snapshot_state(step, extra)
        from r2d2_tpu.replay.snapshot import capture_plain
        # the fused step folds its sample key off train_state.key, which
        # the checkpoint does NOT carry (resume_training_state keeps the
        # reference's no-RNG contract) — the snapshot carries it instead,
        # so a restored learner replays the exact sample stream its
        # uninterrupted twin draws (same contract as service_key above)
        extra = {
            "env_steps": int(self.env_steps),
            "train_key": np.asarray(
                jax.device_get(self.train_state.key)).tolist(),
        }
        if self.mesh is not None:
            extra["next_shard"] = int(self._next_shard)
        return capture_plain(self.spec, self.replay_state, self.ring,
                             step, extra)

    def snapshot_replay(self) -> None:
        """Capture + hand off one durable replay snapshot (ISSUE 18).
        The train path pays only the host capture (device_get of the
        ring state); serialization and the atomic tmp+rename write run
        on the writer thread."""
        if self._snap_writer is None:
            return
        t0 = time.time()
        snap = self._capture_replay()
        self._snap_capture_s = time.time() - t0
        self.tele.observe("recovery/snapshot_capture",
                          self._snap_capture_s)
        self._snap_writer.submit(snap)
        self._snap_adds = self.ring.total_adds

    def recovery_block(self) -> Optional[dict]:
        """The periodic record's ``recovery`` block (attached by the
        orchestrator only when the plane is on, so recovery-off runs
        keep a byte-identical schema). ``lost_blocks_est`` is the adds
        committed since the last snapshot — exactly the experience a
        crash at this instant would cost."""
        if self._snap_writer is None:
            return None
        import os as _os
        w = self._snap_writer
        meta = w.last_meta
        snap = {
            "count": w.count,
            "dropped": w.dropped,
            "age_s": (round(time.time() - meta["written_at"], 3)
                      if meta else None),
            "bytes": meta["payload_bytes"] if meta else None,
            "write_s": meta["write_s"] if meta else None,
            "capture_s": round(self._snap_capture_s, 6),
            "step": meta["step"] if meta else None,
        }
        return {
            "snapshot": snap,
            "restores": self._restores,
            "restored_blocks": self._restored_blocks,
            "lost_blocks_est": max(
                0, self.ring.total_adds - self._snap_adds),
            "supervisor": {"restarts": int(_os.environ.get(
                "R2D2_SUPERVISOR_RESTARTS", "0"))},
        }

    def flush_metrics(self) -> None:
        """Convert accumulated device losses to host floats (ONE sync for the
        whole interval) and feed the training counters. With learning
        diagnostics on, also aggregate the interval's ld/ outputs into the
        record's 'learning' block — and run the NaN forensics there (a
        nan_policy=halt raises out of this flush, stopping the run at the
        log boundary that first observed the poisoned step)."""
        if (not self._costs_attached and self.cfg.telemetry.enabled
                and self.cfg.telemetry.costmodel_enabled):
            # one-shot cost-model block (ISSUE 9): analytic per-component
            # flops/bytes for THIS config — pure host math, no compile —
            # attached at the first flush so the run's very first record
            # carries the compute anatomy the roofline tool elaborates
            self._costs_attached = True
            from r2d2_tpu.telemetry.costmodel import analytic_component_costs
            # self.net holds the RESOLVED bf16 tri-state, so the byte
            # estimates match what this run actually moves
            costs = analytic_component_costs(
                self.cfg, self.net.action_dim,
                act_bytes=2 if self.net.config.bf16 else 4)
            self.metrics.set_costs({
                "model_flops_per_step": costs["model_flops_per_step"],
                "tokens_per_step": costs["tokens_per_step"],
                "components": {
                    name: {"flops": c["flops"], "bytes": c["bytes"]}
                    for name, c in costs["components"].items()},
                "serial_chain": costs["serial_chain"],
            })
        if self._pending_losses:
            t0 = time.time()
            arrays = jax.device_get(self._pending_losses)
            t1 = time.time()
            self.tele.observe("learner/device_sync", t1 - t0)
            self.tele.record_span("learner/device_sync", t0, t1,
                                  {"losses": len(self._pending_losses)})
            self._pending_losses.clear()
            for loss in np.concatenate([np.atleast_1d(a) for a in arrays]):
                self.metrics.on_train_step(float(loss))
        if self._learning_agg is not None:
            pub = (int(self.weight_version_fn())
                   if self.weight_version_fn is not None else None)
            self.metrics.set_learning(self._learning_agg.flush(
                self._host_step, publish_count=pub,
                occupancy_versions=self.ring.live_versions()))
        if self._replay_agg is not None:
            # host placement: the HostReplay numpy twin supplies the
            # sum-tree health + eviction snapshot the external-batch step
            # cannot form in-graph (ISSUE 10)
            host_stats = (self.host_replay.diag_raw()
                          if self.host_mode else None)
            self.metrics.set_replay_diag(
                self._replay_agg.flush(host_stats=host_stats))

    def save(self, index: int) -> str:
        ts = self.train_state
        self._last_saved_step = self._host_step
        path = save_checkpoint(
            self.cfg.runtime.save_dir, self.cfg.env.game_name, index,
            self.player_idx, ts.params, ts.opt_state, ts.target_params,
            int(ts.step), self.env_steps, config_json=self.cfg.to_json())
        if self.cfg.runtime.keep_checkpoints > 0:
            # retention GC (ISSUE 18 satellite): prune after every save
            # so disk growth is bounded at keep_checkpoints orbax dirs
            from r2d2_tpu.runtime.checkpoint import prune_checkpoints
            prune_checkpoints(self.cfg.runtime.save_dir,
                              self.cfg.env.game_name, self.player_idx,
                              self.cfg.runtime.keep_checkpoints)
        return path

    def save_final(self) -> Optional[str]:
        """Preemption-safe final checkpoint: write one last save on a clean
        stop so a preempted run resumes from the stop point, not the last
        periodic interval boundary. No-op when save_interval is unset or
        the current step is already covered by a save (stopping exactly on
        a boundary must not write the same state twice). The index lands
        one past the current periodic slot so it sorts as the newest
        checkpoint for resume. With the recovery plane on, a final replay
        snapshot is written SYNCHRONOUSLY alongside (the process is about
        to exit — a SIGTERM-preempted run resumes with zero replay
        loss)."""
        rt = self.cfg.runtime
        if not rt.save_interval or self._host_step <= self._last_saved_step:
            return None
        path = self.save(self._host_step // rt.save_interval + 1)
        if self._snap_writer is not None:
            self._snap_writer.write_now(self._capture_replay())
            self._snap_adds = self.ring.total_adds
        return path

    def run(self, queue, should_stop: Callable[[], bool],
            max_steps: Optional[int] = None) -> int:
        """Drain + train until should_stop() or max_steps training steps
        (the reference trains for config.training_steps, worker.py:312)."""
        max_steps = max_steps or self.cfg.optim.training_steps
        # initial checkpoint at step 0 (ref worker.py:311)
        if self.cfg.runtime.save_interval:
            self.save(0)
        while not should_stop() and self._host_step < max_steps:
            self.drain(queue)
            if self.ready:
                self.step()
            else:
                time.sleep(0.05)
        self.flush_metrics()
        return self._host_step
