"""Learner host driver around the fused device step.

The reference Learner is a Ray GPU actor with a prefetch thread pulling
batches over RPC and a train thread running torch ops
(/root/reference/worker.py:251-390). Here batches never cross the host
boundary — the fused step samples in HBM — so the host loop is thin: drain
the feeder queue (jitted ring-writes), gate on learning_starts, dispatch
steps, publish weights, checkpoint, count metrics.

Ingestion between steps is the only add/sample interleaving point, which is
what makes the fused step's priority write-back race-free (see
replay/device_replay.py).
"""

import time
from typing import Callable, Optional

import jax
import numpy as np

from r2d2_tpu.config import Config
from r2d2_tpu.learner.train_step import (
    TrainState, create_train_state, make_learner_step)
from r2d2_tpu.models.network import NetworkApply
from r2d2_tpu.replay.device_replay import replay_add, replay_init
from r2d2_tpu.replay.structs import Block, ReplaySpec
from r2d2_tpu.runtime.checkpoint import load_pretrain, save_checkpoint
from r2d2_tpu.runtime.metrics import TrainMetrics


class Learner:
    def __init__(self, cfg: Config, net: NetworkApply, player_idx: int = 0,
                 seed: Optional[int] = None, metrics: Optional[TrainMetrics] = None):
        self.cfg = cfg
        self.net = net
        self.player_idx = player_idx
        self.spec = ReplaySpec.from_config(cfg)
        seed = cfg.runtime.seed if seed is None else seed
        key = jax.random.PRNGKey(seed + 1000 * player_idx)

        self.train_state = create_train_state(key, net, cfg.optim)
        if cfg.runtime.pretrain:
            params = load_pretrain(cfg.runtime.pretrain, self.train_state.params)
            self.train_state = self.train_state.replace(
                params=params,
                target_params=jax.tree_util.tree_map(np.copy, params))
        self.replay_state = replay_init(self.spec)
        self._step_fn = make_learner_step(
            net, self.spec, cfg.optim, cfg.network.use_double)

        self.metrics = metrics or TrainMetrics(player_idx, cfg.runtime.save_dir)
        self.publish: Optional[Callable] = None   # wired by orchestrator

        # Host mirrors of device counters. The learner is the only writer of
        # the ring and the step counter, so mirroring them avoids a blocking
        # device read (a full tunnel round-trip under remote TPU dispatch)
        # per ingested block / per step.
        self.buffer_steps = 0
        self.env_steps = 0
        self._host_ptr = 0
        self._slot_steps = [0] * self.spec.num_blocks
        self._host_step = 0
        self._pending_losses: list = []   # device scalars, flushed lazily

    # -- ingestion --

    def ingest(self, block: Block) -> None:
        """Jitted ring-write of one actor block (ref worker.py:85-120).
        Purely async on device — all counter accounting uses host mirrors."""
        learning = int(np.asarray(block.learning_steps).sum())
        ptr = self._host_ptr
        self.replay_state = replay_add(self.spec, self.replay_state, block)
        # ring overwrite: subtract the steps previously in this slot
        self.buffer_steps += learning - self._slot_steps[ptr]
        self._slot_steps[ptr] = learning
        self._host_ptr = (ptr + 1) % self.spec.num_blocks
        self.env_steps += learning
        ret = float(np.asarray(block.sum_reward))
        self.metrics.on_block(learning, None if np.isnan(ret) else ret)
        self.metrics.set_buffer_size(self.buffer_steps)

    def drain(self, queue, max_items: int = 32) -> int:
        blocks = queue.drain(max_items)
        for blk in blocks:
            self.ingest(blk)
        return len(blocks)

    @property
    def ready(self) -> bool:
        """Training gate (ref worker.py:214-218, config.learning_starts)."""
        return self.buffer_steps >= self.cfg.replay.learning_starts

    @property
    def training_steps(self) -> int:
        """Host-mirrored step counter (no device sync)."""
        return self._host_step

    # -- training --

    def step(self) -> dict:
        """One fused device step. Never blocks on the device: metrics stay
        device arrays until flush_metrics() (called at log time); the step
        counter is host-mirrored."""
        self.train_state, self.replay_state, m = self._step_fn(
            self.train_state, self.replay_state)
        self._host_step += 1
        step = self._host_step
        self._pending_losses.append(m["loss"])

        rt = self.cfg.runtime
        if self.publish is not None and step % rt.weight_publish_interval == 0:
            self.publish(self.train_state.params)
        if rt.save_interval and step % rt.save_interval == 0:
            self.save(step // rt.save_interval)
        return m

    def flush_metrics(self) -> None:
        """Convert accumulated device losses to host floats (ONE sync for the
        whole interval) and feed the training counters."""
        if self._pending_losses:
            losses = np.asarray(jax.device_get(self._pending_losses))
            for loss in losses:
                self.metrics.on_train_step(float(loss))
            self._pending_losses.clear()

    def save(self, index: int) -> str:
        ts = self.train_state
        return save_checkpoint(
            self.cfg.runtime.save_dir, self.cfg.env.game_name, index,
            self.player_idx, ts.params, ts.opt_state, ts.target_params,
            int(ts.step), self.env_steps)

    def run(self, queue, should_stop: Callable[[], bool],
            max_steps: Optional[int] = None) -> int:
        """Drain + train until should_stop() or max_steps training steps
        (the reference trains for config.training_steps, worker.py:312)."""
        max_steps = max_steps or self.cfg.optim.training_steps
        # initial checkpoint at step 0 (ref worker.py:311)
        if self.cfg.runtime.save_interval:
            self.save(0)
        while not should_stop() and self._host_step < max_steps:
            self.drain(queue)
            if self.ready:
                self.step()
            else:
                time.sleep(0.05)
        self.flush_metrics()
        return self._host_step
