"""Actor rollout loop (ref /root/reference/worker.py:528-591) — runs in a
thread (tests) or a spawned process (production) with a CPU-pinned policy.

Per step: policy step → ε-greedy → env.step → frame-stack roll →
LocalBuffer.add; on episode end finish without bootstrap (episode return
reported only from near-greedy actors, ref worker.py:555-556); on block
boundary finish with bootstrap Q; pull fresh weights every
``actor_update_interval`` steps (ref worker.py:567-570 — the reference
hardcodes 400; here the config field is honored).
"""

import time
from typing import Callable, Optional

import numpy as np

from r2d2_tpu.actor.local_buffer import LocalBuffer
from r2d2_tpu.actor.policy import ActorPolicy, BatchedActorPolicy
from r2d2_tpu.config import Config
from r2d2_tpu.replay.structs import ReplaySpec
from r2d2_tpu.telemetry import NULL_TELEMETRY


def make_actor_env(cfg: Config, player_idx: int, actor_idx: int, seed: int,
                   env_factory: Optional[Callable] = None,
                   name: Optional[str] = None, **env_args):
    """The ONE place the scalar-vs-vector env choice and the per-lane seed
    scheme live (seed + lane within the worker's 100-wide seed window —
    Config validates envs_per_actor <= 100). Shared by the thread-mode
    orchestrator, the spawned actor process, the multihost fleet, and the
    throughput bench so the paths cannot drift. ``env_factory`` defaults to
    envs.factory.create_env (injectable for tests); ``name`` defaults to
    the single-host convention (multihost passes its rank-tagged name)."""
    if env_factory is None:
        from r2d2_tpu.envs.factory import create_env
        env_factory = create_env
    if name is None:
        name = f"p{player_idx}a{actor_idx}"
    if cfg.actor.envs_per_actor > 1:
        from r2d2_tpu.envs.vector import make_vector_env
        return make_vector_env(cfg.env, cfg.actor.envs_per_actor, seed=seed,
                               name=name, env_factory=env_factory, **env_args)
    return env_factory(cfg.env, seed=seed, name=name, **env_args)


def make_actor_policy(cfg: Config, net, params, actor_idx: int, seed: int,
                      epsilon: Optional[float] = None,
                      copy_updates: bool = True,
                      total_actors: Optional[int] = None,
                      serve_channel=None, serve_stats=None,
                      should_stop: Optional[Callable[[], bool]] = None,
                      quant_stats=None):
    """Build the policy matching the env shape ``make_actor_env`` produced;
    returns ``(policy, run_loop)`` where ``run_loop`` is run_actor or
    run_vector_actor. ``epsilon`` overrides the scalar path's Ape-X ladder
    value (process actors receive it from the parent); vector lanes always
    take the ladder spread (config.vector_lane_epsilons). Multihost fleets
    pass the GLOBAL ``actor_idx`` and their global worker count as
    ``total_actors`` so the ladder spans the whole fleet.

    ``actor.inference="server"`` (ISSUE 13): the same ladder/seed scheme
    builds a thin Remote(Batched)Policy over ``serve_channel`` instead —
    the ε draws and client ids reproduce the local policies' exactly, so
    a served fleet is action-for-action the local fleet (parity-tested).
    Client-side chaos faults for this slot (disconnect/slow) wrap the
    channel here — the serve twin of instrument_block_sink's injection
    point."""
    from r2d2_tpu.config import apex_epsilon, vector_lane_epsilons
    serve = cfg.actor.inference == "server"
    if serve:
        if serve_channel is None:
            raise ValueError(
                "actor.inference='server' needs a serve_channel (the "
                "spawner connects it to the policy server's transport)")
        if cfg.actor.fault_spec:
            from r2d2_tpu.tools.chaos import parse_fault_spec, wrap_channel
            fault = parse_fault_spec(cfg.actor.fault_spec).get(actor_idx)
            if fault is not None:
                serve_channel = wrap_channel(serve_channel, fault)
        kw = dict(stats=serve_stats,
                  timeout_s=cfg.serve.request_timeout_s,
                  max_retry_s=cfg.serve.max_retry_s,
                  should_stop=should_stop,
                  backoff_base_s=cfg.runtime.restart_backoff_base_s,
                  backoff_max_s=cfg.runtime.restart_backoff_max_s,
                  trace_every=(cfg.telemetry.trace_sample_every
                               if (cfg.telemetry.enabled
                                   and cfg.telemetry.tracing_enabled)
                               else 0))
    # quantized inference (ISSUE 14): local policies run the quantized
    # forward whenever the config knob says so (the knob lives in
    # NetworkConfig, so the policies see it through net); the accuracy
    # probe runs only where a QuantStats can receive its results (thread
    # actors — process children have no channel back to the record, and
    # served workers' forwards probe server-side)
    qkw = {}
    if not serve and cfg.network.inference_dtype != "f32":
        qkw = dict(quant_stats=quant_stats,
                   quant_probe_interval=(
                       cfg.telemetry.quant_probe_interval
                       if quant_stats is not None else 0))
    if cfg.actor.envs_per_actor > 1:
        epsilons = vector_lane_epsilons(actor_idx, cfg.actor, total_actors)
        seeds = [seed + lane for lane in range(cfg.actor.envs_per_actor)]
        if serve:
            from r2d2_tpu.serve import RemoteBatchedPolicy
            policy = RemoteBatchedPolicy(
                serve_channel, net.action_dim, epsilons, seeds,
                client_base=actor_idx * cfg.actor.envs_per_actor, **kw)
        else:
            policy = BatchedActorPolicy(net, params, epsilons, seeds=seeds,
                                        copy_updates=copy_updates, **qkw)
        return policy, run_vector_actor
    if epsilon is None:
        epsilon = apex_epsilon(actor_idx,
                               total_actors or cfg.actor.num_actors,
                               cfg.actor.base_eps, cfg.actor.eps_alpha)
    if serve:
        from r2d2_tpu.serve import RemotePolicy
        policy = RemotePolicy(serve_channel, net.action_dim, epsilon,
                              seed=seed,
                              client_id=actor_idx * cfg.actor.envs_per_actor,
                              **kw)
    else:
        policy = ActorPolicy(net, params, epsilon, seed=seed,
                             copy_updates=copy_updates, **qkw)
    return policy, run_actor


def instrument_block_sink(cfg: Config, slot: int, sink: Callable,
                          board=None, telemetry=None,
                          weight_version: Optional[Callable[[], int]] = None,
                          lane_base: Optional[int] = None,
                          on_leave: Optional[Callable[[], None]] = None,
                          generation: int = 0) -> Callable:
    """Health + telemetry instrumentation around a block sink — the ONE
    wrapping point shared by every actor spawner (thread, process,
    single-host, multihost), so scalar and vector loops alike publish
    heartbeats, honor ``actor.fault_spec``, and time their block emits
    without knowing about any of it. Order: telemetry outermost (an
    injected fault's stall shows up in the 'actor/block_emit' tail —
    that's the point), then heartbeat (the beat marks "reached the sink
    alive", so an injected hang is detected on the regular
    ``hang_timeout_s`` clock, not the spawn grace), then the fault, then
    — innermost, so every path above sees the stamped record — the
    staleness stamp: ``weight_version()`` (the weight service's publish
    count the actor is currently acting with) lands in the block's
    weight_version field, the generation half of the learner's sample-age
    accounting (ISSUE 5). ``slot`` is the fleet-local worker index (the
    HeartbeatBoard row and the fault-spec key).

    ``lane_base`` (ISSUE 10): the worker's first GLOBAL ε-ladder lane
    index. The run loops stamp each block's lane-RELATIVE index (0 for
    the scalar loop, the vector lane otherwise); this sink offsets it to
    the global ladder position — the lane-provenance stamp the learner's
    replay diagnostics attribute sampled batches to. Unknown stays
    unknown: a block that reaches the sink UNstamped (-1 — a producer
    that predates or misses the relative stamp) keeps -1 and lands in
    the composition's unknown bucket rather than being fabricated into
    the worker's first lane."""
    wrapped = sink
    if cfg.telemetry.tracing_enabled:
        # Lineage stamp (ISSUE 19), innermost: EVERY block of a traced
        # run carries the trace_ms leaf (uniform pytrees — stacked
        # groups and the producer pump tree_map over mixed blocks), but
        # only every Nth gets a real emission stamp; the rest stay
        # UNTRACED(-1). Off => the leaf is never attached and blocks
        # are byte-identical to the untraced schema.
        from r2d2_tpu.telemetry.tracing import UNTRACED, now_ms
        _every = max(int(cfg.telemetry.trace_sample_every), 1)
        _emit_count = [0]

        def sink_with_trace(block, _wrapped=wrapped):
            _emit_count[0] += 1
            stamp = now_ms() if _emit_count[0] % _every == 0 else UNTRACED
            return _wrapped(block.replace(
                trace_ms=np.asarray(stamp, np.int32)))
        wrapped = sink_with_trace
    if lane_base is not None:
        def sink_with_lane(block, _wrapped=wrapped, _base=int(lane_base)):
            rel = int(np.asarray(block.lane))
            if rel < 0:
                return _wrapped(block)
            return _wrapped(block.replace(lane=np.asarray(
                _base + rel, np.int32)))
        wrapped = sink_with_lane
    if weight_version is not None:
        def sink_with_stamp(block, _wrapped=wrapped):
            return _wrapped(block.replace(weight_version=np.asarray(
                int(weight_version()), np.int32)))
        wrapped = sink_with_stamp
    if cfg.actor.fault_spec:
        from r2d2_tpu.tools.chaos import (SINK_KINDS_LOCAL,
                                          SINK_KINDS_SERVER, apply_fault,
                                          parse_fault_spec)
        fault = parse_fault_spec(cfg.actor.fault_spec).get(slot)
        # served inference moves slow/disconnect to the REQUEST path
        # (make_actor_policy wraps the serve channel); only the worker-
        # process kinds stay at the sink there
        sink_kinds = (SINK_KINDS_SERVER if cfg.actor.inference == "server"
                      else SINK_KINDS_LOCAL)
        # a LEAVE fault models the slot's ORIGINAL worker departing; a
        # joiner adopting the slot (generation > 0) is a new worker and
        # must not inherit the departure — otherwise a rejoined slot
        # leaves again N blocks after every adoption and the churn
        # drill/A-B measure a permanently-narrowed fleet instead of a
        # bounded gap. Crash/hang faults DO re-apply across respawns
        # (the crash-loop/breaker drills depend on that).
        if (fault is not None and fault.kind == "leave"
                and generation > 0):
            fault = None
        if fault is not None and fault.kind in sink_kinds:
            # on_leave (ISSUE 15): the spawner's membership hook — an
            # injected 'leave' parks the slot for re-adoption BEFORE the
            # worker unwinds, so the supervisor sees a detached slot,
            # never a crash
            wrapped = apply_fault(wrapped, fault, on_leave=on_leave)
    if board is not None:
        def sink_with_heartbeat(block, _wrapped=wrapped):
            board.beat(slot)
            return _wrapped(block)
        wrapped = sink_with_heartbeat
    if telemetry is not None and telemetry.enabled:
        def sink_with_telemetry(block, _wrapped=wrapped):
            t0 = time.time()
            try:
                return _wrapped(block)
            finally:
                t1 = time.time()
                telemetry.observe("actor/block_emit", t1 - t0)
                telemetry.record_span("actor/block_emit", t0, t1,
                                      {"slot": slot})
        wrapped = sink_with_telemetry
    return wrapped


def run_actor(cfg: Config, env, policy: ActorPolicy, block_sink: Callable,
              weight_poll: Callable, should_stop: Callable[[], bool],
              max_env_steps: Optional[int] = None, *,
              telemetry=None, quality_feed=None) -> int:
    """Returns total env steps taken. ``block_sink(block)`` ships a finished
    block; ``weight_poll()`` returns fresh params or None. ``quality_feed``
    (ISSUE 20) is the optional Q-calibration tap handed to the LocalBuffer.

    OWNS ``env`` from here on: closes it on every exit (clean stop or
    crash), in ONE place for all spawners — a respawned actor builds a
    fresh env, and an unclosed predecessor leaks fds/engine handles per
    restart (round-3 advisor)."""
    try:
        return _run_actor(cfg, env, policy, block_sink, weight_poll,
                          should_stop, max_env_steps, telemetry,
                          quality_feed)
    finally:
        try:
            env.close()
        except Exception:
            pass


def _run_actor(cfg: Config, env, policy: ActorPolicy, block_sink: Callable,
               weight_poll: Callable, should_stop: Callable[[], bool],
               max_env_steps: Optional[int] = None, telemetry=None,
               quality_feed=None) -> int:
    tele = telemetry if telemetry is not None else NULL_TELEMETRY
    spec = ReplaySpec.from_config(cfg)
    lb = LocalBuffer(spec, policy.action_dim, cfg.optim.gamma,
                     cfg.optim.priority_eta, quality_feed=quality_feed)

    obs = env.reset()
    policy.observe_reset(obs)
    lb.reset(obs)
    episode_steps = 0
    total_steps = 0
    counter = 0

    while not should_stop():
        # per-step timing goes to histograms only (one integer increment
        # each when telemetry is on; spans stay at block cadence)
        t0 = time.perf_counter()
        action, q, hidden = policy.act()
        t1 = time.perf_counter()
        next_obs, reward, done, _ = env.step(action)
        tele.observe("actor/forward", t1 - t0)
        tele.observe("actor/env_step", time.perf_counter() - t1)
        policy.observe(next_obs, action)
        lb.add(action, reward, next_obs, q, hidden)
        episode_steps += 1
        total_steps += 1

        if done or episode_steps == cfg.actor.max_episode_steps:
            # relative lane 0 (the scalar worker IS its only lane);
            # instrument_block_sink offsets to the global ladder
            block = lb.finish(None).replace(lane=np.asarray(0, np.int32))
            if policy.epsilon > cfg.actor.near_greedy_eps:
                # only near-greedy actors report episode returns
                block = block.replace(sum_reward=np.asarray(np.nan, np.float32))
            block_sink(block)
            obs = env.reset()
            policy.observe_reset(obs)
            lb.reset(obs)
            episode_steps = 0
        elif len(lb) == spec.block_length:
            block_sink(lb.finish(policy.bootstrap_q()).replace(
                lane=np.asarray(0, np.int32)))

        counter += 1
        if counter >= cfg.actor.actor_update_interval:
            t0 = time.perf_counter()
            params = weight_poll()
            if params is not None:
                policy.update_params(params)
            tele.observe("actor/weight_sync", time.perf_counter() - t0)
            counter = 0

        if max_env_steps is not None and total_steps >= max_env_steps:
            break
    return total_steps


def run_vector_actor(cfg: Config, venv, policy: BatchedActorPolicy,
                     block_sink: Callable, weight_poll: Callable,
                     should_stop: Callable[[], bool],
                     max_env_steps: Optional[int] = None, *,
                     telemetry=None, quality_feed=None) -> int:
    """The N-lane twin of ``run_actor``: one jitted (N, 1) policy forward
    steps every lane of a SyncVectorEnv per tick; each lane keeps its own
    LocalBuffer so block content is identical to N scalar actors' (parity-
    tested at N=1 against run_actor). Returns total env steps across lanes.

    OWNS ``venv`` (and through it every lane env) — closes it on every
    exit, same contract as run_actor."""
    try:
        return _run_vector_actor(cfg, venv, policy, block_sink, weight_poll,
                                 should_stop, max_env_steps, telemetry,
                                 quality_feed)
    finally:
        try:
            venv.close()
        except Exception:
            pass


def _run_vector_actor(cfg: Config, venv, policy: BatchedActorPolicy,
                      block_sink: Callable, weight_poll: Callable,
                      should_stop: Callable[[], bool],
                      max_env_steps: Optional[int] = None,
                      telemetry=None, quality_feed=None) -> int:
    tele = telemetry if telemetry is not None else NULL_TELEMETRY
    spec = ReplaySpec.from_config(cfg)
    n = venv.num_envs
    if n != policy.num_lanes:
        raise ValueError(f"venv has {n} lanes but policy has "
                         f"{policy.num_lanes}")
    # lanes share one feed — QualityStats is thread/lane-safe
    buffers = [LocalBuffer(spec, policy.action_dim, cfg.optim.gamma,
                           cfg.optim.priority_eta,
                           quality_feed=quality_feed) for _ in range(n)]

    obs = venv.reset()
    for i in range(n):
        policy.observe_reset_lane(i, obs[i])
        buffers[i].reset(obs[i])
    total_steps = 0
    counter = 0

    while not should_stop():
        # one forward + one vector-env step per tick: the timing unit the
        # histograms see (a 16-lane tick counts once, covering 16 steps)
        t0 = time.perf_counter()
        actions, qs, hiddens = policy.act()
        t1 = time.perf_counter()
        next_obs, rewards, dones, infos = venv.step(actions)
        tele.observe("actor/forward", t1 - t0)
        tele.observe("actor/env_step", time.perf_counter() - t1)
        # advance every lane's policy state BEFORE per-lane bookkeeping:
        # the block-boundary bootstrap reads the post-step state (matching
        # the scalar loop's observe-then-bootstrap order), and done lanes
        # get overwritten by observe_reset_lane below anyway
        policy.observe(next_obs, actions)
        boot_q = None    # lazily computed once per tick, shared by lanes
        for i in range(n):
            lb = buffers[i]
            lb.add(int(actions[i]), float(rewards[i]), next_obs[i],
                   qs[i], hiddens[i])
            # episode accounting lives in the vector env (one source of
            # truth); auto-reset lanes short-circuit on dones[i]
            if dones[i] or venv.episode_steps[i] == cfg.actor.max_episode_steps:
                # lane-RELATIVE provenance stamp (ISSUE 10):
                # instrument_block_sink offsets it to the global ladder
                block = lb.finish(None).replace(
                    lane=np.asarray(i, np.int32))
                if policy.epsilons[i] > cfg.actor.near_greedy_eps:
                    # only near-greedy lanes report episode returns
                    block = block.replace(
                        sum_reward=np.asarray(np.nan, np.float32))
                block_sink(block)
                # auto-reset lanes carry the new episode's initial obs in
                # info; truncated (or non-auto-reset) lanes restart here
                reset_obs = infos[i].get("reset_obs") if dones[i] else None
                if reset_obs is None:
                    reset_obs = venv.reset_lane(i)
                policy.observe_reset_lane(i, reset_obs)
                lb.reset(reset_obs)
            elif len(lb) == spec.block_length:
                if boot_q is None:
                    boot_q = policy.bootstrap_q()
                block_sink(lb.finish(boot_q[i]).replace(
                    lane=np.asarray(i, np.int32)))
        total_steps += n

        counter += n
        if counter >= cfg.actor.actor_update_interval:
            t0 = time.perf_counter()
            params = weight_poll()
            if params is not None:
                policy.update_params(params)
            tele.observe("actor/weight_sync", time.perf_counter() - t0)
            counter = 0

        if max_env_steps is not None and total_steps >= max_env_steps:
            break
    return total_steps
