"""Actor rollout loop (ref /root/reference/worker.py:528-591) — runs in a
thread (tests) or a spawned process (production) with a CPU-pinned policy.

Per step: policy step → ε-greedy → env.step → frame-stack roll →
LocalBuffer.add; on episode end finish without bootstrap (episode return
reported only from near-greedy actors, ref worker.py:555-556); on block
boundary finish with bootstrap Q; pull fresh weights every
``actor_update_interval`` steps (ref worker.py:567-570 — the reference
hardcodes 400; here the config field is honored).
"""

from typing import Callable, Optional

import numpy as np

from r2d2_tpu.actor.local_buffer import LocalBuffer
from r2d2_tpu.actor.policy import ActorPolicy
from r2d2_tpu.config import Config
from r2d2_tpu.replay.structs import ReplaySpec


def run_actor(cfg: Config, env, policy: ActorPolicy, block_sink: Callable,
              weight_poll: Callable, should_stop: Callable[[], bool],
              max_env_steps: Optional[int] = None) -> int:
    """Returns total env steps taken. ``block_sink(block)`` ships a finished
    block; ``weight_poll()`` returns fresh params or None.

    OWNS ``env`` from here on: closes it on every exit (clean stop or
    crash), in ONE place for all spawners — a respawned actor builds a
    fresh env, and an unclosed predecessor leaks fds/engine handles per
    restart (round-3 advisor)."""
    try:
        return _run_actor(cfg, env, policy, block_sink, weight_poll,
                          should_stop, max_env_steps)
    finally:
        try:
            env.close()
        except Exception:
            pass


def _run_actor(cfg: Config, env, policy: ActorPolicy, block_sink: Callable,
               weight_poll: Callable, should_stop: Callable[[], bool],
               max_env_steps: Optional[int] = None) -> int:
    spec = ReplaySpec.from_config(cfg)
    lb = LocalBuffer(spec, policy.action_dim, cfg.optim.gamma,
                     cfg.optim.priority_eta)

    obs = env.reset()
    policy.observe_reset(obs)
    lb.reset(obs)
    episode_steps = 0
    total_steps = 0
    counter = 0

    while not should_stop():
        action, q, hidden = policy.act()
        next_obs, reward, done, _ = env.step(action)
        policy.observe(next_obs, action)
        lb.add(action, reward, next_obs, q, hidden)
        episode_steps += 1
        total_steps += 1

        if done or episode_steps == cfg.actor.max_episode_steps:
            block = lb.finish(None)
            if policy.epsilon > cfg.actor.near_greedy_eps:
                # only near-greedy actors report episode returns
                block = block.replace(sum_reward=np.asarray(np.nan, np.float32))
            block_sink(block)
            obs = env.reset()
            policy.observe_reset(obs)
            lb.reset(obs)
            episode_steps = 0
        elif len(lb) == spec.block_length:
            block_sink(lb.finish(policy.bootstrap_q()))

        counter += 1
        if counter >= cfg.actor.actor_update_interval:
            params = weight_poll()
            if params is not None:
                policy.update_params(params)
            counter = 0

        if max_env_steps is not None and total_steps >= max_env_steps:
            break
    return total_steps
