"""Durable replay snapshots (ISSUE 18 tentpole, plane a).

The prioritized recurrent replay is the expensive state in R2D2 — params
re-materialize from any checkpoint in seconds, but the ring took millions
of env-steps to fill, and before this plane a learner or ReplayService
crash lost every shard's storage rows, sum-tree priorities, stamps, and
spill pages. This module serializes the FULL replay plane to disk and
restores it bit-exactly:

  * per shard: every live ``ReplayState`` leaf (storage rings, sum-tree,
    ring pointer, staleness/lane stamps, and — when replay_diag is on —
    the sample-count ring and eviction accumulators), the
    ``RingAccountant`` host mirror, the spill tier's pages in LRU order
    with their stored priorities (the lazy-deletion heap is rebuilt from
    the per-page priorities, which ``demote``/``write_back`` keep as the
    single source of truth), and the ``_resident``/``_demote_ids``
    demotion shadow;
  * service-level: the round-robin add/sample cursors and the route, so
    a restored service routes the NEXT block exactly where the dead one
    would have;
  * caller extras (the learner rides its service sample key along), so
    resume-determinism holds through the sampling RNG.

Consistency cut: capture runs under the service lock at a commit
boundary (between learner dispatches — the same quiescent point
``replay_add_many`` groups commit at), so a snapshot never splits a
grouped add. None leaves are captured as ABSENT and restored as None —
the kill-switch pytree contract (a restored state compiles the same
programs as the pre-crash one, byte for byte).

Disk format: one ``.npz`` payload + one ``.json`` manifest per player,
each written tmp + ``os.replace``; the MANIFEST rename is the commit
point (a loader that finds a manifest whose payload byte-size matches is
looking at a complete snapshot — a crash mid-write leaves the previous
pair intact). :class:`SnapshotWriter` does the serialization and IO on a
background thread so the train path pays only the host capture
(device_get of the shard states), never the disk.

These page files are also the ROADMAP item-4b substrate: a disk tier
below host RAM demotes/promotes through exactly this per-page layout.
"""

import json
import os
import threading
import time
from collections import OrderedDict
from typing import List, Optional

import numpy as np

SNAPSHOT_VERSION = 1

# ReplaySpec fields a snapshot must agree on to be loadable: everything
# that shapes the state arrays or the sampling programs.
_SPEC_FIELDS = ("num_blocks", "seqs_per_block", "block_length", "burn_in",
                "learning", "forward", "frame_stack", "frame_height",
                "frame_width", "hidden_dim", "batch_size", "prio_exponent",
                "is_exponent", "pallas_gather", "exact_gather",
                "replay_diag")


def snapshot_paths(save_dir: str, player_idx: int):
    """(payload, manifest) paths for one player's rolling snapshot."""
    base = os.path.join(save_dir, f"replay_player{player_idx}")
    return base + ".npz", base + ".json"


def _spec_fingerprint(spec) -> dict:
    return {f: getattr(spec, f) for f in _SPEC_FIELDS}


def _block_fields_np(block) -> dict:
    """Block -> {field: numpy} (None fields omitted — the same record
    the socket frames carry)."""
    return {name: np.asarray(getattr(block, name))
            for name in block.__dataclass_fields__
            if getattr(block, name) is not None}


def _state_to_host(state) -> dict:
    """ReplayState -> {leaf: numpy} for the present (non-None) leaves."""
    import jax
    out = {}
    for name in state.__dataclass_fields__:
        leaf = getattr(state, name)
        if leaf is not None:
            out[name] = np.asarray(jax.device_get(leaf))
    return out


def _put_like(template_leaf, arr: np.ndarray):
    """Re-pin one restored leaf exactly where the freshly-initialized
    template leaf lives (same device/sharding — the replay_add_many
    pinning discipline: donated programs require operands on the layout
    they were compiled for)."""
    import jax
    try:
        return jax.device_put(arr, template_leaf.sharding)
    except (AttributeError, ValueError):
        return jax.device_put(arr)


def _restore_state(template, leaves: dict):
    """Rebuild a ReplayState from captured leaves onto ``template``'s
    placement. The captured leaf set must equal the template's present
    leaf set — a replay_diag (or exact_gather) mismatch means the
    snapshot belongs to a differently-compiled program."""
    present = {name for name in template.__dataclass_fields__
               if getattr(template, name) is not None}
    if present != set(leaves):
        raise ValueError(
            "replay snapshot leaf set "
            f"{sorted(leaves)} != expected {sorted(present)} — the "
            "snapshot was taken under a different replay_diag/gather "
            "configuration; re-run with matching telemetry knobs or "
            "drop the snapshot")
    return template.replace(**{
        name: _put_like(getattr(template, name), leaves[name])
        for name in present})


# ---------------------------------------------------------------------------
# Capture: live objects -> one pure-host snapshot dict.


def _capture_ring(ring) -> dict:
    cap = {
        "ptr": int(ring.ptr),
        "total_adds": int(ring.total_adds),
        "buffer_steps": int(ring.buffer_steps),
        "slot_steps": [int(s) for s in ring.slot_steps],
        "slot_versions": [int(v) for v in ring.slot_versions],
    }
    # Lineage mirrors (ISSUE 19) ride only when something is traced —
    # an untraced run's snapshot stays byte-identical to the PR-18
    # format, and a legacy snapshot restores as all-untraced below.
    trace = getattr(ring, "slot_trace", None)
    ingest = getattr(ring, "slot_ingest_ms", None)
    if trace is not None and any(t >= 0 for t in trace):
        cap["slot_trace"] = [int(t) for t in trace]
        cap["slot_ingest"] = [int(t) for t in ingest]
    return cap


def _capture_shard(shard) -> dict:
    spill = shard.spill
    pages = [(int(pid), _block_fields_np(block), int(learning), int(wv))
             for pid, (block, learning, wv) in spill._pages.items()]
    resident = [(slot, _block_fields_np(blk), int(learning), int(wv))
                for slot, page in enumerate(shard._resident)
                if page is not None
                for blk, learning, wv in [page]]
    return {
        "state": _state_to_host(shard.state),
        "ring": _capture_ring(shard.ring),
        "spill": {
            "next_id": int(spill._next_id),
            "demotions": int(spill.demotions),
            "promotions": int(spill.promotions),
            "evictions": int(spill.evictions),
            "writebacks": int(spill.writebacks),
            # pages ride in OrderedDict (= LRU) order; per-page priority
            # is re-derived into _prio and the heap at restore
            "pages": pages,
        },
        "resident": resident,
        "demote_ids": [(-1 if d is None else int(d))
                       for d in shard._demote_ids],
    }


def capture_service(service, step: int, extra: Optional[dict] = None) -> dict:
    """Consistent cut of a full ReplayService under its lock (call at a
    commit boundary — between learner dispatches). ``extra`` carries
    caller state that must ride the snapshot (the learner's service
    sample key); values must be JSON-serializable."""
    with service._lock:
        return {
            "version": SNAPSHOT_VERSION,
            "kind": "service",
            "step": int(step),
            "spec": _spec_fingerprint(service.spec),
            "route": service.route,
            "rr_add": int(service._rr_add),
            "rr_sample": int(service._rr_sample),
            "extra": dict(extra or {}),
            "shards": [_capture_shard(s) for s in service.shards],
        }


def capture_plain(spec, state, ring, step: int,
                  extra: Optional[dict] = None) -> dict:
    """Consistent cut of the legacy in-mesh device replay (one
    ReplayState + its RingAccountant mirror — the replay_shards=0
    learner). Caller quiesces (the learner's step loop is
    single-threaded between dispatches)."""
    return {
        "version": SNAPSHOT_VERSION,
        "kind": "plain",
        "step": int(step),
        "spec": _spec_fingerprint(spec),
        "extra": dict(extra or {}),
        "shards": [{
            "state": _state_to_host(state),
            "ring": _capture_ring(ring),
        }],
    }


# ---------------------------------------------------------------------------
# Restore: snapshot dict -> live objects (bit-parity with the capture).


def _restore_ring(ring, cap: dict) -> None:
    ring.ptr = int(cap["ptr"])
    ring.total_adds = int(cap["total_adds"])
    ring.buffer_steps = int(cap["buffer_steps"])
    ring.slot_steps = [int(s) for s in cap["slot_steps"]]
    ring.slot_versions = [int(v) for v in cap["slot_versions"]]
    n = len(ring.slot_steps)
    ring.slot_trace = [int(t) for t in cap.get("slot_trace", [-1] * n)]
    ring.slot_ingest_ms = [int(t) for t in cap.get("slot_ingest", [-1] * n)]


def _restore_spill(spill, cap: dict, block_cls) -> None:
    import heapq
    spill._pages = OrderedDict()
    spill._prio = {}
    spill._heap = []
    for pid, fields, learning, wv in cap["pages"]:
        block = block_cls(**fields)
        spill._pages[int(pid)] = (block, int(learning), int(wv))
        prio = float(np.max(np.asarray(block.priority)))
        spill._prio[int(pid)] = prio
        spill._heap.append((-prio, int(pid)))
    heapq.heapify(spill._heap)
    spill._next_id = int(cap["next_id"])
    spill.demotions = int(cap["demotions"])
    spill.promotions = int(cap["promotions"])
    spill.evictions = int(cap["evictions"])
    spill.writebacks = int(cap["writebacks"])


def _check_spec(snap: dict, spec) -> None:
    got, want = snap["spec"], _spec_fingerprint(spec)
    if got != want:
        diff = {k: (got.get(k), want[k]) for k in want
                if got.get(k) != want[k]}
        raise ValueError(
            f"replay snapshot spec mismatch {diff} (snapshot, current) — "
            "the snapshot belongs to a different replay geometry")


def restore_service(service, snap: dict) -> None:
    """Load a captured cut back into a freshly-constructed ReplayService
    (same config): shard states re-pinned onto their template placement,
    accountants/spill/cursors overwritten in place."""
    from r2d2_tpu.replay.structs import Block
    if snap.get("kind") != "service":
        raise ValueError(f"snapshot kind {snap.get('kind')!r} is not a "
                         "service snapshot")
    _check_spec(snap, service.spec)
    if len(snap["shards"]) != service.num_shards:
        raise ValueError(
            f"snapshot has {len(snap['shards'])} shards, service has "
            f"{service.num_shards} — shard count must match to restore")
    if snap["route"] != service.route:
        raise ValueError(
            f"snapshot route {snap['route']!r} != service route "
            f"{service.route!r}")
    with service._lock:
        for shard, cap in zip(service.shards, snap["shards"]):
            shard.state = _restore_state(shard.state, cap["state"])
            _restore_ring(shard.ring, cap["ring"])
            _restore_spill(shard.spill, cap["spill"], Block)
            shard._resident = [None] * shard.spec.num_blocks
            for slot, fields, learning, wv in cap["resident"]:
                shard._resident[int(slot)] = (
                    Block(**fields), int(learning), int(wv))
            shard._demote_ids = [(None if d < 0 else int(d))
                                 for d in cap["demote_ids"]]
        service._rr_add = int(snap["rr_add"])
        service._rr_sample = int(snap["rr_sample"])


def restore_plain(spec, state, ring, snap: dict):
    """Load a plain (in-mesh) cut: returns the restored ReplayState
    (pinned like ``state``) and overwrites ``ring`` in place."""
    if snap.get("kind") != "plain":
        raise ValueError(f"snapshot kind {snap.get('kind')!r} is not a "
                         "plain replay snapshot")
    _check_spec(snap, spec)
    cap = snap["shards"][0]
    _restore_ring(ring, cap["ring"])
    return _restore_state(state, cap["state"])


# ---------------------------------------------------------------------------
# Disk format: flatten the snapshot dict into one npz payload plus a
# JSON manifest; manifest rename is the commit point.


def _common_fields(pages) -> list:
    """Block fields present on EVERY page, in first-page order. Pages
    can disagree on optional trailing leaves (a legacy-restored page has
    no trace_ms while post-restore pages do) — only the common set
    stacks; a dropped optional leaf restores as None/untraced."""
    if not pages:
        return []
    common = set(pages[0][1])
    for _, fields, _, _ in pages[1:]:
        common &= set(fields)
    return [f for f in pages[0][1] if f in common]


def _flatten_payload(snap: dict) -> dict:
    """Everything array-shaped goes into the npz; scalars/structure stay
    in the manifest."""
    arrays = {}
    for j, shard in enumerate(snap["shards"]):
        p = f"s{j}."
        for name, arr in shard["state"].items():
            arrays[p + "state." + name] = arr
        arrays[p + "ring.slot_steps"] = np.asarray(
            shard["ring"]["slot_steps"], np.int64)
        arrays[p + "ring.slot_versions"] = np.asarray(
            shard["ring"]["slot_versions"], np.int64)
        if "slot_trace" in shard["ring"]:
            arrays[p + "ring.slot_trace"] = np.asarray(
                shard["ring"]["slot_trace"], np.int64)
            arrays[p + "ring.slot_ingest"] = np.asarray(
                shard["ring"]["slot_ingest"], np.int64)
        if "spill" in shard:
            pages = shard["spill"]["pages"]
            arrays[p + "spill.ids"] = np.asarray(
                [pid for pid, _, _, _ in pages], np.int64)
            arrays[p + "spill.learning"] = np.asarray(
                [lg for _, _, lg, _ in pages], np.int64)
            arrays[p + "spill.wv"] = np.asarray(
                [wv for _, _, _, wv in pages], np.int64)
            for field in _common_fields(pages):
                arrays[p + "spill.f." + field] = np.stack(
                    [fields[field] for _, fields, _, _ in pages])
            res = shard["resident"]
            arrays[p + "res.slots"] = np.asarray(
                [slot for slot, _, _, _ in res], np.int64)
            arrays[p + "res.learning"] = np.asarray(
                [lg for _, _, lg, _ in res], np.int64)
            arrays[p + "res.wv"] = np.asarray(
                [wv for _, _, _, wv in res], np.int64)
            for field in _common_fields(res):
                arrays[p + "res.f." + field] = np.stack(
                    [fields[field] for _, fields, _, _ in res])
            arrays[p + "demote_ids"] = np.asarray(
                shard["demote_ids"], np.int64)
    return arrays


def _manifest_meta(snap: dict, payload_name: str, payload_bytes: int,
                   duration_s: float) -> dict:
    meta = {
        "version": snap["version"],
        "kind": snap["kind"],
        "step": snap["step"],
        "spec": snap["spec"],
        "extra": snap["extra"],
        "payload": payload_name,
        "payload_bytes": payload_bytes,
        "written_at": time.time(),
        "write_s": round(duration_s, 6),
        "total_adds": sum(s["ring"]["total_adds"] for s in snap["shards"]),
        "shards": [],
    }
    if snap["kind"] == "service":
        meta.update(route=snap["route"], rr_add=snap["rr_add"],
                    rr_sample=snap["rr_sample"])
    for shard in snap["shards"]:
        entry = {
            "state_leaves": sorted(shard["state"]),
            "ring": {k: shard["ring"][k]
                     for k in ("ptr", "total_adds", "buffer_steps")},
        }
        if "spill" in shard:
            entry["spill"] = {k: shard["spill"][k]
                              for k in ("next_id", "demotions",
                                        "promotions", "evictions",
                                        "writebacks")}
            entry["spill"]["occupancy"] = len(shard["spill"]["pages"])
        meta["shards"].append(entry)
    return meta


def write_snapshot(snap: dict, save_dir: str, player_idx: int) -> dict:
    """Persist one snapshot atomically (payload first, then the manifest
    — its rename commits). Returns the manifest dict (the recovery
    telemetry's source: bytes, duration, step, written_at)."""
    os.makedirs(save_dir, exist_ok=True)
    payload_path, manifest_path = snapshot_paths(save_dir, player_idx)
    t0 = time.perf_counter()
    arrays = _flatten_payload(snap)
    tmp = payload_path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, payload_path)
    payload_bytes = os.path.getsize(payload_path)
    meta = _manifest_meta(snap, os.path.basename(payload_path),
                          payload_bytes, time.perf_counter() - t0)
    tmp = manifest_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, manifest_path)
    return meta


def _unstack_pages(data, prefix: str, ids_key: str):
    ids = data[prefix + ids_key]
    n = ids.shape[0]
    learning = data[prefix + "learning"]
    wv = data[prefix + "wv"]
    fields = {k[len(prefix) + 2:]: data[k] for k in data.files
              if k.startswith(prefix + "f.")}
    return [(int(ids[i]), {f: arr[i] for f, arr in fields.items()},
             int(learning[i]), int(wv[i])) for i in range(n)]


def load_snapshot(save_dir: str, player_idx: int) -> Optional[dict]:
    """Read a committed snapshot back into the capture dict shape; None
    when no (complete) snapshot exists. A manifest whose payload is
    missing or size-mismatched is treated as absent (the previous pair
    was already replaced — nothing consistent remains)."""
    payload_path, manifest_path = snapshot_paths(save_dir, player_idx)
    if not os.path.exists(manifest_path):
        return None
    with open(manifest_path) as f:
        meta = json.load(f)
    if meta.get("version") != SNAPSHOT_VERSION:
        raise ValueError(
            f"replay snapshot version {meta.get('version')} != "
            f"{SNAPSHOT_VERSION} at {manifest_path}")
    if (not os.path.exists(payload_path)
            or os.path.getsize(payload_path) != meta["payload_bytes"]):
        return None
    snap = {
        "version": meta["version"],
        "kind": meta["kind"],
        "step": meta["step"],
        "spec": meta["spec"],
        "extra": meta.get("extra", {}),
        "shards": [],
    }
    if meta["kind"] == "service":
        snap.update(route=meta["route"], rr_add=meta["rr_add"],
                    rr_sample=meta["rr_sample"])
    with np.load(payload_path) as data:
        for j, entry in enumerate(meta["shards"]):
            p = f"s{j}."
            shard = {
                "state": {name: data[p + "state." + name]
                          for name in entry["state_leaves"]},
                "ring": {
                    **entry["ring"],
                    "slot_steps": data[p + "ring.slot_steps"].tolist(),
                    "slot_versions":
                        data[p + "ring.slot_versions"].tolist(),
                },
            }
            if p + "ring.slot_trace" in data.files:
                shard["ring"]["slot_trace"] = \
                    data[p + "ring.slot_trace"].tolist()
                shard["ring"]["slot_ingest"] = \
                    data[p + "ring.slot_ingest"].tolist()
            if "spill" in entry:
                shard["spill"] = {
                    **{k: entry["spill"][k]
                       for k in ("next_id", "demotions", "promotions",
                                 "evictions", "writebacks")},
                    "pages": _unstack_pages(data, p + "spill.", "ids"),
                }
                shard["resident"] = _unstack_pages(data, p + "res.",
                                                   "slots")
                shard["demote_ids"] = data[p + "demote_ids"].tolist()
            snap["shards"].append(shard)
    return snap


def read_manifest(save_dir: str, player_idx: int) -> Optional[dict]:
    """Manifest alone (no payload load) — the cheap existence/telemetry
    probe."""
    payload_path, manifest_path = snapshot_paths(save_dir, player_idx)
    if not os.path.exists(manifest_path):
        return None
    try:
        with open(manifest_path) as f:
            meta = json.load(f)
    except (json.JSONDecodeError, OSError):
        return None
    if (not os.path.exists(payload_path)
            or os.path.getsize(payload_path) != meta.get("payload_bytes")):
        return None
    return meta


# ---------------------------------------------------------------------------
# Background writer: the train path hands over a captured cut; the
# serialization and disk IO happen off-thread. Latest-wins: a submit
# while a write is in flight replaces any queued cut (snapshots are
# rolling — only the newest matters).


class SnapshotWriter:
    def __init__(self, save_dir: str, player_idx: int):
        self.save_dir = save_dir
        self.player_idx = player_idx
        self._pending: Optional[dict] = None
        self._cond = threading.Condition()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        # telemetry (read by the recovery block): guarded by _cond
        self.count = 0
        self.dropped = 0            # cuts replaced before they wrote
        self.last_meta: Optional[dict] = None

    def submit(self, snap: dict) -> None:
        """Queue one captured cut for writing (latest wins); lazy-starts
        the writer thread. Re-raises a prior write failure here — a
        snapshot plane that cannot write must fail the run loudly, not
        pretend durability."""
        with self._cond:
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            if self._pending is not None:
                self.dropped += 1
            self._pending = snap
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name=f"replay-snapshot-p{self.player_idx}")
                self._thread.start()
            self._cond.notify()

    def _loop(self) -> None:
        while True:
            with self._cond:
                while self._pending is None and not self._stop:
                    self._cond.wait(timeout=0.25)
                if self._pending is None and self._stop:
                    return
                snap, self._pending = self._pending, None
            try:
                meta = write_snapshot(snap, self.save_dir,
                                      self.player_idx)
            except BaseException as e:   # surfaced at the next submit
                with self._cond:
                    self._error = e
                continue
            with self._cond:
                self.count += 1
                self.last_meta = meta

    def write_now(self, snap: dict) -> dict:
        """Synchronous write (the final-checkpoint path: the process is
        about to exit, so there is no train path to protect). Drains any
        pending async cut first by replacing it — this cut is newer."""
        with self._cond:
            if self._pending is not None:
                self._pending = None
                self.dropped += 1
        meta = write_snapshot(snap, self.save_dir, self.player_idx)
        with self._cond:
            self.count += 1
            self.last_meta = meta
        return meta

    def drain(self, timeout: float = 10.0) -> bool:
        """Block until no cut is pending (test/shutdown hook)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._cond:
                if self._pending is None:
                    return True
            time.sleep(0.005)
        return False

    def stop(self, join_timeout: float = 10.0) -> None:
        self.drain(join_timeout)
        with self._cond:
            self._stop = True
            self._cond.notify()
        if self._thread is not None:
            self._thread.join(timeout=join_timeout)
            self._thread = None
