"""Replay data layout: fixed-shape block records and buffer state.

The reference stores ragged per-block numpy arrays in Python lists
(/root/reference/worker.py:69-78) and slices them with per-sample Python
loops (/root/reference/worker.py:140-166). XLA needs static shapes, so here a
block is a *fixed-shape record* — ragged reality is carried by per-sequence
metadata (burn_in/learning/forward/seq_start) and masks, and the unused tail
of a short block is zero padding that sampling can never select (its tree
leaves get priority 0).

Timeline convention for one block (matches the reference's indexing at
/root/reference/worker.py:143-149): position t in [0, burn_in0 + size) covers
the carried burn-in prefix then the block's new steps. ``obs_row[t + j]``
(j < frame_stack) is the stacked observation fed to the model at step t, with
``frame_stack - 1`` duplicate leading frames at episode start;
``last_action_row[t]`` is the action index taken at step t-1 (-1 = none, which
one-hot-encodes to the reference's zero vector, /root/reference/worker.py:416).
Sequence s starts at timeline ``seq_start[s] = burn_in0 + sum(learning[:s])``
and its sampled window begins at ``seq_start[s] - burn_in[s]``.
"""

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np
from flax import struct

from r2d2_tpu.config import Config
from r2d2_tpu.ops.sum_tree import tree_num_layers


@dataclass(frozen=True)
class ReplaySpec:
    """Static shape/dtype contract shared by device and host replay, the
    actor-side block assembler, and the learner. Hashable → usable as a jit
    static argument."""

    num_blocks: int
    seqs_per_block: int     # S: sequence slots per block
    block_length: int       # steps per block
    burn_in: int            # max burn-in steps
    learning: int           # max learning steps per sequence (L)
    forward: int            # max n-step horizon (F)
    frame_stack: int
    frame_height: int
    frame_width: int
    hidden_dim: int
    batch_size: int
    prio_exponent: float
    is_exponent: float
    # resolved at spec construction (ReplayConfig.pallas_sample_gather
    # tri-state): device-path sampling gathers obs windows with the pallas
    # kernel instead of the XLA gather
    pallas_gather: bool = False
    # ReplayConfig.pallas_exact_gather: pad stored frame height to a
    # sublane multiple and DMA only the sampled window (exact read,
    # async-copy kernel — used when pallas_gather is also on; without it
    # the row gather runs on the padded storage transparently, which is
    # how the CPU test path exercises the layout). The DEVICE obs ring and
    # sampled batches carry stored_frame_height rows; blocks, host replay,
    # and the decoded network input stay at frame_height.
    exact_gather: bool = False
    # Replay & data-pathology observability (ISSUE 10): True allocates the
    # in-graph diagnostic state on the replay ring (per-slot sample-count
    # ring, add-counter birth stamps, eviction accumulators) and routes
    # the sample/add paths through its accounting. Resolved from
    # telemetry.enabled AND telemetry.replay_diag_enabled — False (the
    # kill switch) compiles add/sample programs without any diagnostic
    # state, and the periodic record schema is byte-identical to PR9.
    replay_diag: bool = False

    @classmethod
    def from_config(cls, cfg: Config) -> "ReplaySpec":
        from r2d2_tpu.ops.pallas_kernels import resolve_pallas_setting
        return cls(
            num_blocks=cfg.num_blocks,
            seqs_per_block=cfg.seqs_per_block,
            block_length=cfg.replay.block_length,
            burn_in=cfg.sequence.burn_in_steps,
            learning=cfg.sequence.learning_steps,
            forward=cfg.sequence.forward_steps,
            frame_stack=cfg.env.frame_stack,
            frame_height=cfg.env.frame_height,
            frame_width=cfg.env.frame_width,
            hidden_dim=cfg.network.hidden_dim,
            batch_size=cfg.replay.batch_size,
            prio_exponent=cfg.replay.prio_exponent,
            is_exponent=cfg.replay.importance_sampling_exponent,
            pallas_gather=resolve_pallas_setting(
                cfg.replay.pallas_sample_gather, "pallas_sample_gather"),
            exact_gather=resolve_pallas_setting(
                cfg.replay.pallas_exact_gather, "pallas_exact_gather"),
            replay_diag=(cfg.telemetry.enabled
                         and cfg.telemetry.replay_diag_enabled),
        )

    @property
    def stored_frame_height(self) -> int:
        """Frame height in the DEVICE obs ring under exact_gather: padded
        to the uint8 sublane-packing multiple so window slices are
        tile-aligned for the async-copy DMA; equal to frame_height
        otherwise. The obs ring is uint8, whose TPU tile is (32, 128) —
        1-byte values pack 4 rows per 4-byte sublane — so the pad multiple
        is 32 (84 -> 96), not the f32 tile's 8."""
        if not self.exact_gather:
            return self.frame_height
        return -(-self.frame_height // 32) * 32

    @property
    def stored_frame_width(self) -> int:
        """Frame width in the DEVICE obs ring under exact_gather: padded to
        the 128-lane tile. Mosaic requires BOTH minor dims of an HBM
        memref slice to be tile-aligned — an H-only pad was rejected on
        v5e ('slice along dimension 3 must be aligned to tiling (128), but
        is 84', BENCH r4). The decode strips the padding
        (stack_frames out_width), so the network still sees frame_width.

        STORAGE COST: the pad grows the whole obs ring 1.74x in HBM
        (96*128 vs 84*84 bytes per frame at reference scale) — the price
        of exact window reads. A production-capacity ring sized near the
        HBM limit can OOM at replay_init with exact_gather on; weigh that
        against the 7.7x -> 1.74x read-amplification win (PERF.md)."""
        if not self.exact_gather:
            return self.frame_width
        return -(-self.frame_width // 128) * 128

    @property
    def device_ring_bytes(self) -> int:
        """Estimated HBM footprint of one ReplayState at replay_init —
        exact for the arrays it allocates (obs ring dominating; padded
        dims under exact_gather). Used by the replay_init capacity guard
        so an oversized ring is refused with numbers instead of OOMing,
        and available to CLIs for config-time validation. Note
        dp-sharding does NOT divide this: each shard holds a full ring
        (sharded_replay_init)."""
        n, s, l = self.num_blocks, self.seqs_per_block, self.learning
        obs = (n * self.obs_row_len
               * self.stored_frame_height * self.stored_frame_width)
        last_action = n * self.la_row_len * 4
        hidden = n * s * 2 * self.hidden_dim * 4
        # action/reward/gamma (n,s,l) + 4 per-sequence i32 fields
        seq_meta = n * s * (3 * l + 4) * 4
        # per-block weight-version + lane-provenance stamps
        versions = 2 * n * 4
        tree = (2 ** self.tree_layers - 1) * 4
        # replay diagnostics (ISSUE 10): sample-count + birth-stamp rings,
        # the add counter, eviction accumulators, lifetime histogram
        diag = (2 * n + 1 + 5 + 64) * 4 if self.replay_diag else 0
        return obs + last_action + hidden + seq_meta + versions + tree + diag

    @property
    def seq_window(self) -> int:
        """Unrolled steps per sampled sequence (ref config.py:51 seq_len)."""
        return self.burn_in + self.learning + self.forward

    @property
    def obs_row_len(self) -> int:
        """Frames stored per block row. Covers the last sequence's full
        (padded) window: worst-case window start is burn_in0 + block_length -
        learning - burn_in, so the row must extend forward past the last
        learning step by the full ``forward`` horizon plus stacking margin."""
        return self.burn_in + self.block_length + self.forward + self.frame_stack - 1

    @property
    def la_row_len(self) -> int:
        return self.burn_in + self.block_length + self.forward

    @property
    def num_sequences(self) -> int:
        return self.num_blocks * self.seqs_per_block

    @property
    def tree_layers(self) -> int:
        return tree_num_layers(self.num_sequences)


class Block(struct.PyTreeNode):
    """One actor-produced block, fixed shape (device-ingestable as-is).

    The reference's 12-tuple (/root/reference/worker.py:86-91,492) with the
    ragged fields padded; ``sum_reward`` is NaN when no finished episode
    should be reported (reference uses None, /root/reference/worker.py:554-556).
    """

    obs_row: jnp.ndarray       # (obs_row_len, H, W) uint8
    last_action_row: jnp.ndarray  # (la_row_len,) int32, -1 = null
    hidden: jnp.ndarray        # (S, 2, hidden_dim) f32
    action: jnp.ndarray        # (S, L) int32
    reward: jnp.ndarray        # (S, L) f32 — n-step discounted returns
    gamma: jnp.ndarray         # (S, L) f32 — effective discount on bootstrap
    priority: jnp.ndarray      # (S,) f32 — initial |mixed TD|, 0 for empty slots
    burn_in_steps: jnp.ndarray  # (S,) int32
    learning_steps: jnp.ndarray  # (S,) int32 — 0 for empty slots
    forward_steps: jnp.ndarray  # (S,) int32
    seq_start: jnp.ndarray     # (S,) int32 — timeline offset of first learning step
    num_sequences: jnp.ndarray  # () int32
    sum_reward: jnp.ndarray    # () f32, NaN = do not report
    # Generation stamp for staleness accounting (ISSUE 5): the weight
    # service's PUBLISH COUNT the producing actor was acting with when it
    # emitted this block (stamped by instrument_block_sink). Trailing and
    # defaulted so pre-stamp (PR4-era) block records still construct —
    # -1 = unknown, reported as such rather than crashing.
    weight_version: jnp.ndarray = struct.field(
        default_factory=lambda: np.full((), -1, np.int32))  # () int32
    # Lane provenance (ISSUE 10): the GLOBAL ε-ladder lane index that
    # produced this block. Run loops stamp their lane-relative index and
    # instrument_block_sink offsets it to the fleet-global ladder position
    # (the on-device acting path stamps the global index in-graph). Same
    # trailing-defaulted pattern as the PR5 staleness stamp: PR5-era block
    # records without the field load as lane -1 = unknown.
    lane: jnp.ndarray = struct.field(
        default_factory=lambda: np.full((), -1, np.int32))  # () int32
    # Lineage trace stamp (ISSUE 19): wall-clock emission time in ms mod
    # 2^31 on the SAMPLED fraction of blocks a tracing run stamps
    # (telemetry.tracing_enabled + trace_sample_every). None-default —
    # NOT default_factory — so the leaf is absent from untraced blocks:
    # addw socket frames (the omit-None _block_fields contract), block
    # snapshots, and every compiled add program stay byte-identical with
    # tracing off, and pre-PR19 block records load as "untraced". The
    # replay service strips the leaf before device commit and carries
    # the stamp in the ring accountant's host mirrors instead.
    trace_ms: jnp.ndarray = None  # () int32, -1 = untraced


class ReplayState(struct.PyTreeNode):
    """Device-resident buffer state. Donated through jitted add/train steps so
    XLA updates it in place (no copy of the multi-GB obs ring)."""

    tree: jnp.ndarray          # (2**tree_layers - 1,) f32 priority sum tree
    obs: jnp.ndarray           # (N, obs_row_len, H, W) uint8
    last_action: jnp.ndarray   # (N, la_row_len) int32
    hidden: jnp.ndarray        # (N, S, 2, hidden_dim) f32
    action: jnp.ndarray        # (N, S, L) int32
    reward: jnp.ndarray        # (N, S, L) f32
    gamma: jnp.ndarray         # (N, S, L) f32
    burn_in_steps: jnp.ndarray  # (N, S) int32
    learning_steps: jnp.ndarray  # (N, S) int32
    forward_steps: jnp.ndarray  # (N, S) int32
    seq_start: jnp.ndarray     # (N, S) int32
    weight_version: jnp.ndarray  # (N,) int32 — per-block generation stamp
    block_ptr: jnp.ndarray     # () int32 ring pointer
    # Lane provenance ring (ISSUE 10): the producing ε-lane of each block
    # row (-1 = unknown / pre-stamp). Trailing + defaulted (a None leaf
    # drops from the pytree) so directly-constructed states in tests and
    # external pipelines keep working; replay_init always allocates it.
    lane: jnp.ndarray = None   # (N,) int32
    # -- replay-diagnostics state (ISSUE 10; allocated only under
    # spec.replay_diag — None leaves vanish from the pytree, so the kill
    # switch compiles the PR9 programs byte-for-byte) --
    sample_count: jnp.ndarray = None     # (N,) int32 — times any sequence
                                         # of the block was sampled
    added_at: jnp.ndarray = None         # (N,) int32 — add-counter value
                                         # when the block landed
    add_count: jnp.ndarray = None        # () int32 — monotonic adds
    # eviction accumulators, updated at overwrite in replay_add_many:
    # [evicted, never_sampled, lifetime_sum, age_sum,
    # final_priority_sum] — ages in ring adds (blocks), lifetimes in
    # times-sampled. SINCE-LAST-SNAPSHOT deltas: the diagnostics
    # snapshot (telemetry/replaydiag.fused_replay_diag) reads AND
    # resets them each interval, so the counts stay far below f32's
    # 2^24 exact-integer ceiling on runs of any length; cumulative
    # totals integrate host-side in float64 (ReplayDiagAggregator).
    evict_stats: jnp.ndarray = None      # (5,) float32
    # histogram (shared 64-bucket log layout) of times-sampled at
    # eviction, over evicted slots that WERE sampled (the never-sampled
    # count lives in evict_stats); reset with it
    evict_life_hist: jnp.ndarray = None  # (64,) int32


class SampleBatch(struct.PyTreeNode):
    """One training batch of sequences, still in storage dtypes (uint8 obs,
    index actions) — decode/normalize happens inside the train step where XLA
    fuses it into the conv (ref does /255 on GPU too, worker.py:330-331)."""

    obs: jnp.ndarray           # (B, seq_window + stack - 1, H, W) uint8
    last_action: jnp.ndarray   # (B, seq_window) int32
    hidden: jnp.ndarray        # (B, 2, hidden_dim) f32
    action: jnp.ndarray        # (B, L) int32
    reward: jnp.ndarray        # (B, L) f32
    gamma: jnp.ndarray         # (B, L) f32
    burn_in_steps: jnp.ndarray  # (B,) int32
    learning_steps: jnp.ndarray  # (B,) int32
    forward_steps: jnp.ndarray  # (B,) int32
    is_weights: jnp.ndarray    # (B,) f32
    idxes: jnp.ndarray         # (B,) int32 — tree leaf indices for write-back
    # (B,) int32 per-sequence generation stamp (the containing block's
    # weight_version; -1 = unknown). Trailing + defaulted: externally
    # assembled batches (tests, synthetic pipelines) that predate the
    # stamp keep constructing; a None leaf is dropped from the pytree, so
    # every jitted consumer that ignores it compiles unchanged.
    weight_version: jnp.ndarray = None
    # (B,) int32 producing ε-lane of each sequence (the containing
    # block's lane stamp; -1 = unknown) — same trailing-defaulted
    # contract as weight_version (ISSUE 10).
    lane: jnp.ndarray = None


class RingAccountant:
    """The single host-side authority for block-ring accounting: pointer
    advance, per-slot learning-step counts, total buffered steps, and the
    monotonic add counter behind the staleness guard.

    Exists so the wrap rule lives in ONE place (VERDICT r2 weak #5: the
    Learner, HostReplay, and the jitted replay_add each used to keep their
    own pointer arithmetic, consistent only by convention). HostReplay owns
    one; in host placement the Learner reads the SAME instance, and in
    device placement the Learner's instance is the host mirror of the
    compiled pointer in ReplayState.block_ptr (replay_add advances it with
    the identical `(ptr + 1) % num_blocks` rule — asserted equal in
    tests/test_replay.py)."""

    def __init__(self, num_blocks: int):
        self.num_blocks = num_blocks
        self.ptr = 0
        self.total_adds = 0        # monotonic; never wraps
        self.slot_steps = [0] * num_blocks
        self.buffer_steps = 0      # live learning steps across the ring
        # per-slot generation stamp (the landed block's weight_version;
        # -1 = empty or unstamped) — the host mirror behind the learner's
        # replay-occupancy age percentiles (ISSUE 5)
        self.slot_versions = [-1] * num_blocks
        # lineage trace mirrors (ISSUE 19): the landed block's emission
        # stamp (Block.trace_ms, stripped before device commit) and the
        # wall-ms it was committed — both -1 for untraced slots, so an
        # untraced run's accounting is unchanged beyond two idle lists.
        self.slot_trace = [-1] * num_blocks
        self.slot_ingest_ms = [-1] * num_blocks

    def advance(self, learning_steps: int, weight_version: int = -1,
                trace_ms: int = -1, ingest_ms: int = -1) -> int:
        """Account one block write: returns the slot it lands in and rolls
        the pointer, replacing the overwritten slot's step count."""
        slot = self.ptr
        self.buffer_steps += learning_steps - self.slot_steps[slot]
        self.slot_steps[slot] = learning_steps
        self.slot_versions[slot] = int(weight_version)
        self.slot_trace[slot] = int(trace_ms)
        self.slot_ingest_ms[slot] = int(ingest_ms)
        self.ptr = (slot + 1) % self.num_blocks
        self.total_adds += 1
        return slot

    def live_versions(self):
        """Generation stamps of the slots currently holding data — the
        occupancy-age source (unstamped live slots report -1 = unknown)."""
        return [v for v, steps in zip(self.slot_versions, self.slot_steps)
                if steps > 0]

    def stale_adds(self, adds_snapshot: int) -> int:
        return self.total_adds - adds_snapshot


def empty_block_np(spec: ReplaySpec) -> dict:
    """Zeroed numpy block record (host-side assembly scratch)."""
    return dict(
        obs_row=np.zeros((spec.obs_row_len, spec.frame_height, spec.frame_width), np.uint8),
        last_action_row=np.full((spec.la_row_len,), -1, np.int32),
        hidden=np.zeros((spec.seqs_per_block, 2, spec.hidden_dim), np.float32),
        action=np.zeros((spec.seqs_per_block, spec.learning), np.int32),
        reward=np.zeros((spec.seqs_per_block, spec.learning), np.float32),
        gamma=np.zeros((spec.seqs_per_block, spec.learning), np.float32),
        priority=np.zeros((spec.seqs_per_block,), np.float32),
        burn_in_steps=np.zeros((spec.seqs_per_block,), np.int32),
        learning_steps=np.zeros((spec.seqs_per_block,), np.int32),
        forward_steps=np.zeros((spec.seqs_per_block,), np.int32),
        seq_start=np.zeros((spec.seqs_per_block,), np.int32),
        num_sequences=np.zeros((), np.int32),
        sum_reward=np.full((), np.nan, np.float32),
        weight_version=np.full((), -1, np.int32),
        lane=np.full((), -1, np.int32),
    )
