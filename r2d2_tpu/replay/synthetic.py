"""Reference-shaped synthetic block builder — shared by bench.py and the
production soak (tools/soak.py) so the two can never construct divergent
data when the Block schema changes.

The shapes mirror what LocalBuffer emits at the reference configuration
(/root/reference/worker.py:86-91,492): a full block of S sequences with a
carried burn-in prefix, random frames/actions/rewards, and the last
sequence's forward horizon truncated to 1 as at an episode end.
"""

import numpy as np


def make_synthetic_block(spec, rng):
    from r2d2_tpu.replay.structs import Block
    S, L = spec.seqs_per_block, spec.learning
    burn = np.minimum(np.arange(S) * L, spec.burn_in).astype(np.int32)
    return Block(
        obs_row=rng.integers(0, 255, (spec.obs_row_len, spec.frame_height,
                                      spec.frame_width)).astype(np.uint8),
        last_action_row=rng.integers(
            0, 18, (spec.la_row_len,)).astype(np.int32),
        hidden=rng.normal(size=(S, 2, spec.hidden_dim)).astype(np.float32),
        action=rng.integers(0, 18, (S, L)).astype(np.int32),
        reward=rng.normal(size=(S, L)).astype(np.float32),
        gamma=np.full((S, L), 0.997**spec.forward, np.float32),
        priority=rng.uniform(0.1, 2.0, (S,)).astype(np.float32),
        burn_in_steps=burn,
        learning_steps=np.full((S,), L, np.int32),
        forward_steps=np.concatenate(
            [np.full((S - 1,), spec.forward), [1]]).astype(np.int32),
        seq_start=(burn[0] + L * np.arange(S)).astype(np.int32),
        num_sequences=np.asarray(S, np.int32),
        sum_reward=np.asarray(np.nan, np.float32),
    )
