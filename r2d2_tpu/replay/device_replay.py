"""HBM-resident prioritized sequence replay — jitted add / sample / update.

The reference replay is a dedicated CPU process: numba sum-tree walks plus a
128-iteration Python slice loop per batch, reached through a Ray RPC
(/root/reference/worker.py:122-190). Here the whole buffer lives in HBM as
fixed-shape rings and all three operations are XLA programs:

  * ``replay_add``     — ring-write one block + seed its tree priorities
                         (ref worker.py:85-120);
  * ``replay_sample``  — stratified tree descent + batched dynamic-slice
                         gather of sequence windows (ref worker.py:122-190);
  * ``replay_update_priorities`` — write back learner TD priorities
                         (ref worker.py:192-209).

Because the learner fuses sample→train→update into ONE program, sampling and
its priority write-back are atomic with respect to block ingestion — the
reference's ring-pointer staleness guard (/root/reference/worker.py:196-206)
is unnecessary by construction: an ``add`` can never interleave between a
sample and its update.

All entry points donate the state argument, so XLA aliases the multi-GB obs
ring in place instead of copying it.
"""

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from r2d2_tpu.ops.sum_tree import tree_update, tree_sample
from r2d2_tpu.replay.structs import Block, ReplaySpec, ReplayState, SampleBatch


_PAD_WARN_BYTES = 2 << 30     # exact_gather pad warning floor (ADVICE r4)


def _guard_device_capacity(spec: ReplaySpec) -> None:
    """Refuse a ring that cannot fit in device memory with a clear,
    numeric message instead of OOMing mid-init (VERDICT r4 #3), and warn
    once when the exact_gather storage pad makes a large ring materially
    larger — the pad is easy to miss because the flag defaults on for TPU."""
    ring = spec.device_ring_bytes
    dev = jax.devices()[0]
    limit = None
    if dev.platform == "tpu":
        # the ONE memory_stats wrapper (telemetry/resources.py): same
        # backend-optional semantics — {} when the backend reports nothing
        from r2d2_tpu.telemetry.resources import device_memory_stats
        limit = device_memory_stats(dev).get("bytes_limit")
    if limit and ring > 0.9 * limit:
        hint = ""
        if spec.exact_gather:
            import dataclasses
            unpadded = dataclasses.replace(spec, exact_gather=False)
            hint = ("; replay.pallas_exact_gather='off' shrinks storage "
                    f"to ~{_gib(unpadded.device_ring_bytes)} (row-gather "
                    "reads instead of exact-window DMAs)")
        raise ValueError(
            f"device replay ring needs ~{_gib(ring)} but the device "
            f"reports {_gib(limit)} HBM — it would OOM at replay_init. "
            "Reduce replay.capacity or replay.block_length, use "
            f"replay.placement='host'{hint}.")
    if spec.exact_gather and ring > _PAD_WARN_BYTES:
        import warnings
        true_frame = spec.frame_height * spec.frame_width
        pad_frame = spec.stored_frame_height * spec.stored_frame_width
        warnings.warn(
            f"replay.pallas_exact_gather pads stored frames "
            f"{spec.frame_height}x{spec.frame_width} -> "
            f"{spec.stored_frame_height}x{spec.stored_frame_width} "
            f"({pad_frame / true_frame:.2f}x): the obs ring costs "
            f"~{_gib(ring)} in device memory. Set it 'off' for rings "
            "near the HBM limit.")


def _gib(b: float) -> str:
    return f"{b / 2**30:.1f} GiB"


def replay_init(spec: ReplaySpec) -> ReplayState:
    _guard_device_capacity(spec)
    n, s, l = spec.num_blocks, spec.seqs_per_block, spec.learning
    # replay diagnostics state (ISSUE 10): allocated only under the
    # pillar's kill switch — absent (None) leaves drop from the pytree,
    # so the compiled add/sample/step programs are byte-identical to the
    # pre-diagnostics ones when it is off
    diag = {}
    if spec.replay_diag:
        diag = dict(
            sample_count=jnp.zeros((n,), jnp.int32),
            added_at=jnp.zeros((n,), jnp.int32),
            add_count=jnp.zeros((), jnp.int32),
            evict_stats=jnp.zeros((5,), jnp.float32),
            evict_life_hist=jnp.zeros((64,), jnp.int32),
        )
    return ReplayState(
        tree=jnp.zeros(2**spec.tree_layers - 1, jnp.float32),
        # stored_frame_height/_width: tile-padded under spec.exact_gather
        obs=jnp.zeros((n, spec.obs_row_len, spec.stored_frame_height,
                       spec.stored_frame_width), jnp.uint8),
        last_action=jnp.full((n, spec.la_row_len), -1, jnp.int32),
        hidden=jnp.zeros((n, s, 2, spec.hidden_dim), jnp.float32),
        action=jnp.zeros((n, s, l), jnp.int32),
        reward=jnp.zeros((n, s, l), jnp.float32),
        gamma=jnp.zeros((n, s, l), jnp.float32),
        burn_in_steps=jnp.zeros((n, s), jnp.int32),
        learning_steps=jnp.zeros((n, s), jnp.int32),
        forward_steps=jnp.zeros((n, s), jnp.int32),
        seq_start=jnp.zeros((n, s), jnp.int32),
        weight_version=jnp.full((n,), -1, jnp.int32),
        block_ptr=jnp.zeros((), jnp.int32),
        lane=jnp.full((n,), -1, jnp.int32),
        **diag,
    )


@functools.partial(jax.jit, static_argnums=0, donate_argnums=1)
def replay_add(spec: ReplaySpec, state: ReplayState, block: Block) -> ReplayState:
    """Ring-write ``block`` at block_ptr and seed its sequence priorities.

    Empty sequence slots carry priority 0 (their leaves become unsamplable)
    and learning_steps 0, which also re-zeroes slots left over from a longer
    block previously in this ring position.

    Exactly the K=1 case of ``replay_add_many`` — one write path, so a
    Block/ReplayState field added to one cannot silently diverge from the
    other."""
    return replay_add_many(
        spec, state,
        jax.tree_util.tree_map(lambda x: jnp.asarray(x)[None], block))


@functools.partial(jax.jit, static_argnums=0, donate_argnums=1)
def replay_add_many(spec: ReplaySpec, state: ReplayState,
                    blocks: Block) -> ReplayState:
    """Ring-write K stacked blocks in ONE dispatch — parity-exact with K
    sequential ``replay_add`` calls, including ring wrap.

    ``blocks`` is a Block whose every leaf carries a leading K axis (the
    feeder's stacked drain). Block k lands in ring row
    ``(block_ptr + k) % num_blocks`` — the same rows the sequential path
    visits — and all K * seqs_per_block tree leaves are seeded by one
    ``tree_update``. Requires K <= num_blocks: beyond that the scatter rows
    alias (XLA scatter-set order over duplicates is undefined), and the
    sequential path's later-write-wins overwrite cannot be reproduced.
    K is a static shape, so each distinct drain size compiles once.
    """
    k = blocks.priority.shape[0]
    if k > spec.num_blocks:
        raise ValueError(
            f"replay_add_many got {k} blocks but the ring has only "
            f"{spec.num_blocks} rows — scatter rows would alias; cap "
            "replay.ingest_batch_blocks / fleet.ingest_batch_blocks "
            "(or the per-shard actor.anakin_lanes lane group) at "
            "num_blocks — note a sharded service ring has only "
            "num_blocks // fleet.replay_shards rows per shard")
    ptr = state.block_ptr
    rows = (ptr + jnp.arange(k, dtype=jnp.int32)) % spec.num_blocks
    idxes = (rows[:, None] * spec.seqs_per_block
             + jnp.arange(spec.seqs_per_block, dtype=jnp.int32)[None, :]
             ).reshape(-1)
    # eviction accounting (ISSUE 10): read the overwritten rows' lifetime
    # state BEFORE the tree update clobbers their leaf priorities. Rows
    # are distinct (k <= num_blocks, asserted above) so the batched read
    # sees exactly what K sequential adds would have seen row by row —
    # parity-tested against the sequential reference.
    diag = {}
    if spec.replay_diag and state.sample_count is not None:
        with jax.named_scope("replay_diag_evict"):
            live = (jnp.sum(state.learning_steps[rows], axis=1) > 0)  # (k,)
            counts = state.sample_count[rows].astype(jnp.float32)
            # row j is overwritten by the batch's j-th add, so its age is
            # measured against add_count + j — exactly the counter value
            # the sequential path would have seen (parity-tested)
            ages = (state.add_count + jnp.arange(k, dtype=jnp.int32)
                    - state.added_at[rows]).astype(jnp.float32)
            leaf0 = 2 ** (spec.tree_layers - 1) - 1
            prio_row = jnp.max(
                state.tree[leaf0 + idxes].reshape(k, spec.seqs_per_block),
                axis=1)
            livef = live.astype(jnp.float32)
            from r2d2_tpu.telemetry.histogram import value_counts
            diag = dict(
                sample_count=state.sample_count.at[rows].set(0),
                added_at=state.added_at.at[rows].set(
                    state.add_count + jnp.arange(k, dtype=jnp.int32)),
                add_count=state.add_count + k,
                evict_stats=state.evict_stats + jnp.stack([
                    jnp.sum(livef),
                    jnp.sum(livef * (counts == 0)),
                    jnp.sum(livef * counts),
                    jnp.sum(livef * ages),
                    jnp.sum(livef * prio_row)]),
                evict_life_hist=state.evict_life_hist + value_counts(
                    counts, mask=(live & (counts > 0)).astype(jnp.int32)),
            )
    tree = tree_update(spec.tree_layers, state.tree, spec.prio_exponent,
                       blocks.priority.reshape(-1), idxes)
    obs_rows = blocks.obs_row
    if (spec.stored_frame_height != spec.frame_height
            or spec.stored_frame_width != spec.frame_width):
        obs_rows = jnp.pad(obs_rows, (
            (0, 0), (0, 0),
            (0, spec.stored_frame_height - spec.frame_height),
            (0, spec.stored_frame_width - spec.frame_width)))
    return state.replace(
        tree=tree,
        obs=state.obs.at[rows].set(obs_rows),
        last_action=state.last_action.at[rows].set(blocks.last_action_row),
        hidden=state.hidden.at[rows].set(blocks.hidden),
        action=state.action.at[rows].set(blocks.action),
        reward=state.reward.at[rows].set(blocks.reward),
        gamma=state.gamma.at[rows].set(blocks.gamma),
        burn_in_steps=state.burn_in_steps.at[rows].set(blocks.burn_in_steps),
        learning_steps=state.learning_steps.at[rows].set(
            blocks.learning_steps),
        forward_steps=state.forward_steps.at[rows].set(blocks.forward_steps),
        seq_start=state.seq_start.at[rows].set(blocks.seq_start),
        weight_version=state.weight_version.at[rows].set(
            blocks.weight_version.astype(jnp.int32)),
        block_ptr=(ptr + k) % spec.num_blocks,
        **({"lane": state.lane.at[rows].set(
            blocks.lane.astype(jnp.int32))}
           if state.lane is not None else {}),
        **diag,
    )


def _gather_windows(spec: ReplaySpec, state: ReplayState,
                    block_idx: jnp.ndarray, window_start: jnp.ndarray
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Batched gather of (obs, last_action) windows.

    window_start is the timeline offset ``seq_start - burn_in`` (>= 0 by
    construction of the block assembler); rows are padded so the full
    fixed-length window is always in bounds — no clamping can shift data.

    The obs gather is the dominant cost of sampling (52 MB of uint8 per
    batch); spec.pallas_gather routes it to the scalar-prefetch pallas
    kernel on TPU (2.6x the XLA gather, BENCH_r03). last_action is 28 KB —
    the vmapped slice is fine everywhere."""
    from r2d2_tpu.ops.pallas_kernels import gather_rows
    obs_len = spec.seq_window + spec.frame_stack - 1
    obs = gather_rows(state.obs, block_idx, window_start, obs_len,
                      use_pallas=spec.pallas_gather,
                      exact_read=spec.exact_gather)

    def one_la(b, t0):
        return jax.lax.dynamic_slice(state.last_action[b], (t0,),
                                     (spec.seq_window,))

    return obs, jax.vmap(one_la)(block_idx, window_start)


@functools.partial(jax.jit, static_argnums=0)
def replay_sample(spec: ReplaySpec, state: ReplayState, key: jax.Array) -> SampleBatch:
    """Stratified prioritized sample of ``spec.batch_size`` sequences."""
    idxes, is_weights = tree_sample(
        spec.tree_layers, state.tree, spec.is_exponent, spec.batch_size, key)
    block_idx = idxes // spec.seqs_per_block
    seq_idx = idxes % spec.seqs_per_block

    burn_in = state.burn_in_steps[block_idx, seq_idx]
    learning = state.learning_steps[block_idx, seq_idx]
    forward = state.forward_steps[block_idx, seq_idx]
    seq_start = state.seq_start[block_idx, seq_idx]
    obs, last_action = _gather_windows(spec, state, block_idx, seq_start - burn_in)

    return SampleBatch(
        obs=obs,
        last_action=last_action,
        hidden=state.hidden[block_idx, seq_idx],
        action=state.action[block_idx, seq_idx],
        reward=state.reward[block_idx, seq_idx],
        gamma=state.gamma[block_idx, seq_idx],
        burn_in_steps=burn_in,
        learning_steps=learning,
        forward_steps=forward,
        is_weights=is_weights,
        idxes=idxes,
        weight_version=state.weight_version[block_idx],
        # lane provenance rides every batch (like weight_version); an
        # externally-built state without the ring field yields None and
        # consumers skip it
        lane=(state.lane[block_idx] if state.lane is not None else None),
    )


@functools.partial(jax.jit, static_argnums=0, donate_argnums=1)
def replay_update_priorities(spec: ReplaySpec, state: ReplayState,
                             idxes: jnp.ndarray, td_errors: jnp.ndarray
                             ) -> ReplayState:
    """Standalone priority write-back (host-driven pipelines). The fused
    learner step calls tree_update directly instead."""
    tree = tree_update(spec.tree_layers, state.tree, spec.prio_exponent,
                       td_errors, idxes)
    return state.replace(tree=tree)


def replay_size(state: ReplayState) -> jnp.ndarray:
    """Total stored learning steps (ref worker.py:81-82 __len__)."""
    return jnp.sum(state.learning_steps)
