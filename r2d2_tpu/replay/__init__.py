"""Prioritized sequence replay.

Two placements (config.replay.placement):
  * "device" — HBM-resident block-ring with jitted add/sample/priority-update;
    the learner's sample→train→update is one fused XLA program that never
    stalls on a host-side tree walk (the reference pays a Ray round-trip plus
    a numba tree walk per batch, /root/reference/worker.py:299-306,122-190).
  * "host"   — numpy block-ring fed by the native C++ sum tree, mirroring the
    reference's CPU buffer process for machines where HBM is scarce.
"""

from r2d2_tpu.replay.structs import Block, ReplaySpec, ReplayState, SampleBatch
from r2d2_tpu.replay.device_replay import (
    replay_init,
    replay_add,
    replay_add_many,
    replay_sample,
    replay_update_priorities,
)
from r2d2_tpu.replay.host_replay import HostReplay

__all__ = [
    "Block",
    "ReplaySpec",
    "ReplayState",
    "SampleBatch",
    "replay_init",
    "replay_add",
    "replay_add_many",
    "replay_sample",
    "replay_update_priorities",
    "HostReplay",
]
