"""Host (CPU) replay twin — numpy block-ring with the same Block/SampleBatch
contract as the device path.

Serves two roles: (a) the ``placement="host"`` configuration for machines
where HBM is scarce (the reference's CPU buffer process,
/root/reference/worker.py:29-234, minus Ray); (b) the test oracle the jitted
device path is checked against. Uses the native C++ sum tree when built
(r2d2_tpu/native), else the numpy twin.

Unlike the device path, sampling here can race with the learner's async
priority write-back, so a staleness guard drops updates for overwritten ring
slots (the reference's guard, /root/reference/worker.py:196-206, compares raw
ring pointers and silently fails when the ring wraps back to exactly the
snapshot pointer or laps it; here a monotonic add counter closes that hole).
"""

import threading
from typing import Optional, Tuple

import numpy as np

from r2d2_tpu.ops.sum_tree import tree_init_np, tree_sample_np, tree_update_np
from r2d2_tpu.replay.structs import (
    Block, ReplaySpec, RingAccountant, SampleBatch)


class HostReplay:
    def __init__(self, spec: ReplaySpec, seed: int = 0, use_native: bool = True):
        self.spec = spec
        self.rng = np.random.default_rng(seed)
        self.lock = threading.Lock()

        self._native = None
        if use_native:
            try:
                from r2d2_tpu.native import NativeSumTree
                self._native = NativeSumTree(spec.num_sequences)
            except Exception:
                self._native = None
        if self._native is None:
            self.tree_layers, self.tree = tree_init_np(spec.num_sequences)

        n, s, l = spec.num_blocks, spec.seqs_per_block, spec.learning
        self.obs = np.zeros((n, spec.obs_row_len, spec.frame_height, spec.frame_width), np.uint8)
        self.last_action = np.full((n, spec.la_row_len), -1, np.int32)
        self.hidden = np.zeros((n, s, 2, spec.hidden_dim), np.float32)
        self.action = np.zeros((n, s, l), np.int32)
        self.reward = np.zeros((n, s, l), np.float32)
        self.gamma = np.zeros((n, s, l), np.float32)
        self.burn_in_steps = np.zeros((n, s), np.int32)
        self.learning_steps = np.zeros((n, s), np.int32)
        self.forward_steps = np.zeros((n, s), np.int32)
        self.seq_start = np.zeros((n, s), np.int32)
        self.weight_version = np.full((n,), -1, np.int32)
        self.lane = np.full((n,), -1, np.int32)
        # single authority for pointer/step accounting; in host placement
        # the Learner reads this same instance (no mirrored pointer)
        self.ring = RingAccountant(n)
        # replay diagnostics (ISSUE 10), the numpy twin of the device
        # path's in-graph accounting: per-slot sample counts + birth
        # stamps, cumulative eviction accumulators (same 5-element layout
        # as ReplayState.evict_stats), a lifetime histogram on the shared
        # 64-bucket log layout, and a leaf-priority mirror — the native
        # C++ tree does not expose its leaves, so the mirror (one scatter
        # per update) is what sum-tree health reads under either backend.
        self._diag = spec.replay_diag
        if self._diag:
            self.sample_count = np.zeros((n,), np.int64)
            self.added_at = np.zeros((n,), np.int64)
            self.evict_stats = np.zeros((5,), np.float64)
            self.evict_life_hist = np.zeros((64,), np.int64)
            self.leaf_prio = np.zeros((spec.num_sequences,), np.float64)

    # -- sum-tree indirection (native C++ or numpy) --

    def _tree_update(self, td_errors: np.ndarray, idxes: np.ndarray) -> None:
        if self._diag:
            # the leaf mirror applies the EXACT priority rule the trees do
            # (tree_update/tree_update_np): p = |td|**alpha, 0 stays 0
            td = np.asarray(td_errors, np.float64)
            self.leaf_prio[np.asarray(idxes, np.int64)] = np.where(
                td != 0.0, np.abs(td) ** self.spec.prio_exponent, 0.0)
        if self._native is not None:
            self._native.update(self.spec.prio_exponent, td_errors, idxes)
        else:
            tree_update_np(self.tree_layers, self.tree, self.spec.prio_exponent,
                           td_errors, idxes)

    def _tree_sample(self, batch: int) -> Tuple[np.ndarray, np.ndarray]:
        if self._native is not None:
            return self._native.sample(self.spec.is_exponent, batch, self.rng)
        return tree_sample_np(self.tree_layers, self.tree, self.spec.is_exponent,
                              batch, self.rng)

    # -- replay API --

    def add(self, block: Block) -> None:
        spec = self.spec
        with self.lock:
            wv = int(np.asarray(block.weight_version))
            if self._diag:
                # eviction accounting for the slot about to be
                # overwritten — BEFORE advance/tree writes, mirroring the
                # device path's read-before-update order
                slot = self.ring.ptr
                if self.ring.slot_steps[slot] > 0:
                    life = int(self.sample_count[slot])
                    age = float(self.ring.total_adds - self.added_at[slot])
                    lo = slot * spec.seqs_per_block
                    prio = float(
                        self.leaf_prio[lo:lo + spec.seqs_per_block].max())
                    self.evict_stats += [1.0, float(life == 0), float(life),
                                         age, prio]
                    if life > 0:
                        from r2d2_tpu.telemetry.histogram import bucket_index
                        self.evict_life_hist[bucket_index(float(life))] += 1
                self.sample_count[self.ring.ptr] = 0
                self.added_at[self.ring.ptr] = self.ring.total_adds
            ptr = self.ring.advance(
                int(np.asarray(block.learning_steps).sum()), wv)
            self.weight_version[ptr] = wv
            self.lane[ptr] = int(np.asarray(block.lane))
            idxes = ptr * spec.seqs_per_block + np.arange(spec.seqs_per_block, dtype=np.int64)
            self._tree_update(np.asarray(block.priority, np.float64), idxes)
            self.obs[ptr] = block.obs_row
            self.last_action[ptr] = block.last_action_row
            self.hidden[ptr] = block.hidden
            self.action[ptr] = block.action
            self.reward[ptr] = block.reward
            self.gamma[ptr] = block.gamma
            self.burn_in_steps[ptr] = block.burn_in_steps
            self.learning_steps[ptr] = block.learning_steps
            self.forward_steps[ptr] = block.forward_steps
            self.seq_start[ptr] = block.seq_start

    def sample(self, batch_size: Optional[int] = None) -> Tuple[SampleBatch, int]:
        """Returns (batch, total_adds_snapshot) — the snapshot feeds the
        staleness guard in update_priorities."""
        spec = self.spec
        batch = batch_size or spec.batch_size
        with self.lock:
            idxes, is_weights = self._tree_sample(batch)
            idxes = idxes.astype(np.int64)
            b = idxes // spec.seqs_per_block
            s = idxes % spec.seqs_per_block
            if self._diag:
                # times-sampled per block row (duplicates accumulate —
                # np.add.at, matching the device scatter-add)
                np.add.at(self.sample_count, b, 1)

            burn_in = self.burn_in_steps[b, s]
            learning = self.learning_steps[b, s]
            forward = self.forward_steps[b, s]
            start = self.seq_start[b, s] - burn_in

            # batched fancy-index gather: window offsets broadcast over
            # arange(obs_len) — one vectorized take instead of a per-row
            # Python slice loop (the reference's worker.py:140-166 shape)
            obs_len = spec.seq_window + spec.frame_stack - 1
            t0 = start[:, None].astype(np.int64)
            obs = self.obs[b[:, None], t0 + np.arange(obs_len)]
            la = self.last_action[b[:, None], t0 + np.arange(spec.seq_window)]

            return (
                SampleBatch(
                    obs=obs,
                    last_action=la,
                    hidden=self.hidden[b, s],
                    action=self.action[b, s],
                    reward=self.reward[b, s],
                    gamma=self.gamma[b, s],
                    burn_in_steps=burn_in,
                    learning_steps=learning,
                    forward_steps=forward,
                    is_weights=is_weights.astype(np.float32),
                    idxes=idxes.astype(np.int32),
                    weight_version=self.weight_version[b],
                    lane=self.lane[b],
                ),
                self.ring.total_adds,
            )

    def update_priorities(self, idxes: np.ndarray, td_errors: np.ndarray,
                          adds_snapshot: int) -> None:
        """Drop updates for ring slots overwritten since the sample was taken
        (ref worker.py:196-206). ``adds_snapshot`` is the total_adds value
        returned by sample(); being monotonic it detects full ring laps that
        raw pointer comparison cannot.

        This host ring DROPS stale rows outright (they left the buffer
        for good). The sharded service (fleet/replay_service.py) keeps
        the same mask shape but, when a spill tier retains evicted
        blocks, ROUTES stale rows to the demoted page's priority array
        instead of dropping them — a promoted page then re-enters the
        ring with the learner's freshest TD estimates."""
        spec = self.spec
        idxes = np.asarray(idxes, np.int64)
        td_errors = np.asarray(td_errors, np.float64)
        with self.lock:
            adds = self.ring.stale_adds(adds_snapshot)
            if adds >= spec.num_blocks:
                return  # the whole ring was rewritten; everything is stale
            if adds > 0:
                block_ptr = self.ring.ptr
                old_ptr = (block_ptr - adds) % spec.num_blocks
                if block_ptr > old_ptr:
                    mask = (idxes < old_ptr * spec.seqs_per_block) | (
                        idxes >= block_ptr * spec.seqs_per_block)
                else:  # wrapped: stale range is [old_ptr, N) U [0, block_ptr)
                    mask = (idxes < old_ptr * spec.seqs_per_block) & (
                        idxes >= block_ptr * spec.seqs_per_block)
                idxes, td_errors = idxes[mask], td_errors[mask]
            if idxes.size:
                self._tree_update(td_errors, idxes)

    def diag_raw(self) -> Optional[dict]:
        """Raw replay-diagnostics readings for the host-placement learner
        (ISSUE 10) — the numpy twin of the device path's interval
        snapshot, in the SAME layout the ReplayDiagAggregator derives
        from: 5-element tree moments [active, sum, sum_sq, max, at_max],
        the leaf-priority histogram over active leaves (shared 64-bucket
        log layout, parity-tested against the device value_counts), and
        the eviction accumulators — READ AND RESET, like the device
        path's snapshot, so the aggregator integrates cumulative totals
        in one place. None when the diagnostics are off for this spec."""
        if not self._diag:
            return None
        from r2d2_tpu.telemetry.histogram import value_counts_np
        from r2d2_tpu.telemetry.replaydiag import _AT_MAX_RTOL
        with self.lock:
            leaves = self.leaf_prio
            active_mask = leaves > 0
            active = float(active_mask.sum())
            mx = float(leaves.max()) if active else 0.0
            at_max = float(np.sum(
                active_mask & (leaves >= mx * (1.0 - _AT_MAX_RTOL)))) \
                if active else 0.0
            # vectorized (one log10 + bincount): this runs under the
            # replay lock, so a per-leaf Python loop would stall
            # sample()/add() for the whole flush on production rings
            hist = value_counts_np(leaves, mask=active_mask)
            ev, self.evict_stats = self.evict_stats, np.zeros(
                (5,), np.float64)
            lh, self.evict_life_hist = self.evict_life_hist, np.zeros(
                (64,), np.int64)
            return {
                "tree_moments": np.asarray(
                    [active, float(leaves.sum()),
                     float(np.sum(leaves ** 2)), mx, at_max], np.float64),
                "leaf_hist": hist,
                "evict_stats": ev,
                "evict_life_hist": lh,
            }

    def __len__(self) -> int:
        return int(self.learning_steps.sum())
