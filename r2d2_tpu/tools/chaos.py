"""Deterministic fault-injection harness for the worker-health subsystem.

The reference has no failure handling at all (SURVEY §5.3) and therefore
nothing to test failures against; this framework's supervisor, hang
watchdog, restart backoff, and circuit breaker (runtime/feeder.py) are only
trustworthy if they are exercised by REAL killed/wedged workers, not
synthetic stubs. This module is that exerciser:

  * ``parse_fault_spec`` / ``FaultSpec``: the grammar behind the
    ``actor.fault_spec`` config hook — ';'-joined ``slot:kind`` entries,
    deterministic at block granularity so every actor mode (thread,
    process, scalar, vector) misbehaves at exactly the same point:

        1:crash@block=3     slot 1 raises on its 3rd block emit (1-based)
        2:hang@block=5      slot 2 wedges forever at its 5th emit
        0:slow@factor=4     slot 0's emit interval stretched 4x (alias 0:slowx4)

  * ``apply_fault``: wraps a block sink with one fault. Injection lives at
    the sink because every actor loop funnels through it — the one
    choke-point shared by run_actor, run_vector_actor, thread workers, and
    spawned processes (runtime/actor_loop.instrument_block_sink).

  * ``run_chaos``: a self-contained chaos phase (also ``tools/soak.py
    --chaos-seconds`` and ``python -m r2d2_tpu.tools.chaos``): train on the
    fake env with a crash-looping slot and a hanging slot injected, and
    report what supervision did about it (restarts, hangs detected,
    breaker trips, parked slots) alongside proof training kept advancing.
"""

import time
from dataclasses import dataclass
from typing import Callable, Dict

_KINDS = ("crash", "hang", "slow")


class ChaosFault(RuntimeError):
    """Raised by an injected crash fault (distinguishable from real bugs)."""


@dataclass(frozen=True)
class FaultSpec:
    kind: str            # "crash" | "hang" | "slow"
    block: int = 0       # 1-based emit ordinal triggering crash/hang
    factor: float = 1.0  # slow-down multiplier (slow only)


def parse_fault_spec(spec: str) -> Dict[int, FaultSpec]:
    """Parse ``actor.fault_spec`` into {slot: FaultSpec}; raises ValueError
    on malformed input so a bad spec fails at Config construction, not
    mid-run inside a spawned worker."""
    faults: Dict[int, FaultSpec] = {}
    for entry in filter(None, (e.strip() for e in spec.split(";"))):
        slot_s, sep, rest = entry.partition(":")
        if not sep or not rest:
            raise ValueError(
                f"fault_spec entry {entry!r}: expected 'slot:kind[@...]'")
        try:
            slot = int(slot_s)
        except ValueError:
            raise ValueError(
                f"fault_spec entry {entry!r}: slot must be an integer") \
                from None
        if slot < 0:
            raise ValueError(f"fault_spec entry {entry!r}: slot must be >= 0")
        if slot in faults:
            raise ValueError(f"fault_spec: duplicate slot {slot}")
        kind, _, params = rest.partition("@")
        kv = {}
        if params:
            k, sep, v = params.partition("=")
            if not sep:
                raise ValueError(
                    f"fault_spec entry {entry!r}: expected '@key=value'")
            kv[k] = v
        if kind.startswith("slowx"):                # shorthand: slowx4
            kind, kv = "slow", {"factor": kind[len("slowx"):]}
        if kind not in _KINDS:
            raise ValueError(
                f"fault_spec entry {entry!r}: unknown kind {kind!r} "
                f"(expected one of {_KINDS})")
        if kind in ("crash", "hang"):
            try:
                block = int(kv.get("block", ""))
            except ValueError:
                raise ValueError(
                    f"fault_spec entry {entry!r}: {kind} needs @block=N") \
                    from None
            if block < 1:
                raise ValueError(
                    f"fault_spec entry {entry!r}: block must be >= 1 "
                    "(1-based emit ordinal)")
            faults[slot] = FaultSpec(kind, block=block)
        else:
            try:
                factor = float(kv.get("factor", ""))
            except ValueError:
                raise ValueError(
                    f"fault_spec entry {entry!r}: slow needs @factor=F "
                    "(or the slowxF shorthand)") from None
            if factor <= 1.0:
                raise ValueError(
                    f"fault_spec entry {entry!r}: slow factor must be > 1")
            faults[slot] = FaultSpec("slow", factor=factor)
    return faults


def apply_fault(sink: Callable, fault: FaultSpec) -> Callable:
    """Wrap a block sink with one injected fault. Crash raises ChaosFault
    INSTEAD of emitting block N (the worker dies with the block in hand —
    the mid-production death shape); hang wedges there forever (a truly
    unresponsive worker: it ignores stop signals by design, so only the
    watchdog can clear it); slow sleeps (factor-1) x the observed
    inter-emit interval, genuinely stretching block production by
    ``factor`` without guessing at step timings."""
    state = {"emitted": 0, "last": None}

    def faulty_sink(block):
        state["emitted"] += 1
        if fault.kind == "crash" and state["emitted"] >= fault.block:
            raise ChaosFault(
                f"injected crash at block emit {state['emitted']}")
        if fault.kind == "hang" and state["emitted"] >= fault.block:
            while True:             # deliberately ignores every stop signal
                time.sleep(0.25)
        if fault.kind == "slow" and state["last"] is not None:
            # cap one sleep at 5s so a long first interval (compile) does
            # not turn the slow fault into an accidental hang
            time.sleep(min((fault.factor - 1.0)
                           * (time.monotonic() - state["last"]), 5.0))
        state["last"] = time.monotonic()
        return sink(block)

    return faulty_sink


# ---------------------------------------------------------------------------
# Chaos phase: injected faults against the real orchestrator (fake env).


def run_chaos(seconds: float = 60.0, actor_mode: str = "process",
              config_overrides: dict = None) -> dict:
    """Train on the fake env with one healthy, one crash-looping, and one
    hanging actor injected; return a JSON-able report of what supervision
    did (the soak's chaos phase, and ``python -m r2d2_tpu.tools.chaos``).

    The crash-looping slot must trip the circuit breaker and park; the
    hanging slot must be watchdog-killed and respawned with backoff; the
    learner must keep training on the healthy slot throughout."""
    from r2d2_tpu.config import Config
    from r2d2_tpu.runtime.orchestrator import train

    overrides = {
        "env.game_name": "Fake",
        "env.frame_height": 24, "env.frame_width": 24, "env.frame_stack": 2,
        "network.hidden_dim": 16, "network.cnn_out_dim": 32,
        "network.conv_layers": ((8, 4, 2), (16, 3, 1)),
        "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
        "sequence.forward_steps": 3,
        "replay.capacity": 800, "replay.block_length": 20,
        "replay.batch_size": 8, "replay.learning_starts": 100,
        "actor.num_actors": 3,
        "actor.fault_spec": "1:crash@block=2;2:hang@block=2",
        "runtime.save_interval": 0, "runtime.log_interval": 2.0,
        "runtime.steps_per_dispatch": 1,
        "runtime.supervise_interval_s": 0.5,
        "runtime.hang_timeout_s": 4.0,
        "runtime.hang_spawn_grace_s": 90.0,
        "runtime.restart_backoff_base_s": 0.5,
        "runtime.restart_backoff_max_s": 4.0,
        # breaker threshold per mode: thread respawns are cheap, so the
        # crash-loop shows one backed-off respawn before parking (trips on
        # the 3rd failure); a process crash cycle costs a full child
        # bring-up (tens of seconds of jax import + env construction), so
        # the default budget only fits two — park on the 2nd failure (the
        # backoff ladder itself is proven by the thread phase and the unit
        # tests)
        "runtime.max_restarts_per_window": 2 if actor_mode == "thread" else 1,
        "runtime.restart_window_s": 300.0,
        "runtime.ingest_stall_timeout_s": 0.0,
    }
    overrides.update(config_overrides or {})
    cfg = Config().replace(**overrides)

    records = []
    t0 = time.time()
    stacks = train(cfg, max_training_steps=10**9, max_seconds=seconds,
                   actor_mode=actor_mode, log_fn=records.append)
    stack = stacks[0]
    report = {
        "metric": "chaos", "actor_mode": actor_mode,
        "duration_s": round(time.time() - t0, 1),
        "fault_spec": cfg.actor.fault_spec,
        "training_steps": stack.learner.training_steps,
        "env_steps": stack.learner.env_steps,
        **stack.health.snapshot(),
        "heartbeat_counts": [int(c) for c in stack.health.board.counts()],
        "records": records[-3:],
    }
    report["verdict"] = {
        "trained_through_faults": stack.learner.training_steps > 0,
        "hang_detected": stack.health.hangs_detected >= 1,
        "restarts_happened": stack.health.restarts >= 1,
    }
    if actor_mode == "thread":
        # required only where the budget guarantees enough crash cycles:
        # a process crash cycle costs a full child bring-up (tens of
        # seconds under CPU contention), so short process-mode runs may
        # legitimately end before the breaker threshold — the trip still
        # shows up in actor_breaker_trips/actor_parked_slots when reached,
        # and the deterministic breaker guarantees live in
        # tests/test_chaos.py
        report["verdict"]["breaker_parked_crash_loop"] = \
            stack.health.breaker_trips >= 1
    return report


def main(argv=None) -> int:
    import argparse
    import json
    import sys

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seconds", type=float, default=60.0)
    p.add_argument("--actor-mode", choices=("thread", "process"),
                   default="process")
    p.add_argument("--override", action="append", default=[],
                   help="dotted config override key=value (repeatable)")
    args = p.parse_args(argv)
    overrides = {}
    for ov in args.override:
        k, _, v = ov.partition("=")
        try:
            overrides[k] = json.loads(v)
        except (json.JSONDecodeError, ValueError):
            overrides[k] = v
    out = run_chaos(args.seconds, args.actor_mode, overrides)
    print(json.dumps(out))
    ok = all(out["verdict"].values())
    print(f"chaos: verdict={'PASS' if ok else 'FAIL'} {out['verdict']}",
          file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    import sys
    sys.exit(main())
