"""Deterministic fault-injection harness for the worker-health subsystem.

The reference has no failure handling at all (SURVEY §5.3) and therefore
nothing to test failures against; this framework's supervisor, hang
watchdog, restart backoff, and circuit breaker (runtime/feeder.py) are only
trustworthy if they are exercised by REAL killed/wedged workers, not
synthetic stubs. This module is that exerciser:

  * ``parse_fault_spec`` / ``FaultSpec``: the grammar behind the
    ``actor.fault_spec`` config hook — ';'-joined ``slot:kind`` entries,
    deterministic at block granularity so every actor mode (thread,
    process, scalar, vector) misbehaves at exactly the same point:

        1:crash@block=3     slot 1 raises on its 3rd block emit (1-based)
        2:hang@block=5      slot 2 wedges forever at its 5th emit
        0:slow@factor=4     slot 0's emit interval stretched 4x (alias 0:slowx4)
        0:drop_ack@every=3  replay-service server drops every 3rd data ack
                            (ISSUE 16 — feed spec.block into
                            ReplayServiceServer(drop_ack_every=...))

  * ``apply_fault``: wraps a block sink with one fault. Injection lives at
    the sink because every actor loop funnels through it — the one
    choke-point shared by run_actor, run_vector_actor, thread workers, and
    spawned processes (runtime/actor_loop.instrument_block_sink).

  * ``run_chaos``: a self-contained chaos phase (also ``tools/soak.py
    --chaos-seconds`` and ``python -m r2d2_tpu.tools.chaos``): train on the
    fake env with a crash-looping slot and a hanging slot injected, and
    report what supervision did about it (restarts, hangs detected,
    breaker trips, parked slots) alongside proof training kept advancing.
"""

import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional

_KINDS = ("crash", "hang", "slow", "disconnect", "leave", "join",
          "drop_ack")
# kinds that inject at the worker's BLOCK SINK vs at its SERVE CLIENT
# (actor.inference="server"): crash/hang are about the worker process
# and stay at the sink either way; slow moves to the request path in
# served mode (a laggy client against the micro-batcher); disconnect
# only exists at the client (there is no connection to drop locally).
# Membership kinds (ISSUE 15): ``leave`` injects at the sink (the
# worker departs cleanly after its Nth emit and its slot PARKS for
# re-adoption); ``join`` is a FLEET-level schedule, not a worker fault
# — parse_join_spec extracts it and the supervisor admits the joiner.
SINK_KINDS_LOCAL = ("crash", "hang", "slow", "leave")
SINK_KINDS_SERVER = ("crash", "hang", "leave")
CLIENT_KINDS = ("disconnect", "slow")


class ChaosFault(RuntimeError):
    """Raised by an injected crash fault (distinguishable from real bugs)."""


class ChaosLeave(RuntimeError):
    """Raised by an injected ``leave`` fault: the worker departs the
    running fleet — its slot has already been parked for re-adoption
    via the sink's on_leave hook, so supervision treats the corpse as a
    detached slot, not a failure."""


@dataclass(frozen=True)
class FaultSpec:
    kind: str            # "crash" | "hang" | "slow" | "disconnect" |
    #                      "leave" | "join"
    block: int = 0       # 1-based emit ordinal (crash/hang/leave) or
    #                      request period (disconnect@req=N)
    factor: float = 1.0  # slow-down multiplier (slow only)
    t: float = 0.0       # run-relative seconds (join@t=S only)


def _iter_entries(spec: str):
    """Shared entry parser: yields (slot, kind, kv, entry) with the
    slot/kind syntax validated — both parse_fault_spec and
    parse_join_spec consume it, so one bad entry fails identically
    through either."""
    for entry in filter(None, (e.strip() for e in spec.split(";"))):
        slot_s, sep, rest = entry.partition(":")
        if not sep or not rest:
            raise ValueError(
                f"fault_spec entry {entry!r}: expected 'slot:kind[@...]'")
        try:
            slot = int(slot_s)
        except ValueError:
            raise ValueError(
                f"fault_spec entry {entry!r}: slot must be an integer") \
                from None
        if slot < 0:
            raise ValueError(f"fault_spec entry {entry!r}: slot must be >= 0")
        kind, _, params = rest.partition("@")
        kv = {}
        if params:
            k, sep, v = params.partition("=")
            if not sep:
                raise ValueError(
                    f"fault_spec entry {entry!r}: expected '@key=value'")
            kv[k] = v
        if kind.startswith("slowx"):                # shorthand: slowx4
            kind, kv = "slow", {"factor": kind[len("slowx"):]}
        if kind not in _KINDS:
            raise ValueError(
                f"fault_spec entry {entry!r}: unknown kind {kind!r} "
                f"(expected one of {_KINDS})")
        yield slot, kind, kv, entry


def parse_join_spec(spec: str) -> Dict[int, FaultSpec]:
    """Extract the MEMBERSHIP join schedule (``slot:join@t=S``) from a
    fault spec: {slot: FaultSpec("join", t=S)}. Joins are fleet-level
    events (the supervisor admits a joiner into the parked/spare slot
    at t >= S), so they live beside — not instead of — the same slot's
    worker fault (``0:leave@block=3;0:join@t=12`` is the leave-then-
    rejoin drill)."""
    joins: Dict[int, FaultSpec] = {}
    for slot, kind, kv, entry in _iter_entries(spec):
        if kind != "join":
            continue
        if slot in joins:
            raise ValueError(f"fault_spec: duplicate join for slot {slot}")
        try:
            t = float(kv.get("t", ""))
        except ValueError:
            raise ValueError(
                f"fault_spec entry {entry!r}: join needs @t=S "
                "(run-relative seconds)") from None
        if t < 0:
            raise ValueError(
                f"fault_spec entry {entry!r}: join t must be >= 0")
        joins[slot] = FaultSpec("join", t=t)
    return joins


def parse_fault_spec(spec: str) -> Dict[int, FaultSpec]:
    """Parse ``actor.fault_spec`` into {slot: FaultSpec} of WORKER
    faults (join entries are fleet-level; parse_join_spec extracts
    those); raises ValueError on malformed input so a bad spec fails at
    Config construction, not mid-run inside a spawned worker."""
    faults: Dict[int, FaultSpec] = {}
    for slot, kind, kv, entry in _iter_entries(spec):
        if kind == "join":
            continue
        if slot in faults:
            raise ValueError(f"fault_spec: duplicate slot {slot}")
        if kind in ("crash", "hang", "leave"):
            try:
                block = int(kv.get("block", ""))
            except ValueError:
                raise ValueError(
                    f"fault_spec entry {entry!r}: {kind} needs @block=N") \
                    from None
            if block < 1:
                raise ValueError(
                    f"fault_spec entry {entry!r}: block must be >= 1 "
                    "(1-based emit ordinal)")
            faults[slot] = FaultSpec(kind, block=block)
        elif kind == "disconnect":
            # client-side serve fault (ISSUE 13): drop the worker's serve
            # connection every Nth request — lease release + reconnect
            try:
                req = int(kv.get("req", ""))
            except ValueError:
                raise ValueError(
                    f"fault_spec entry {entry!r}: disconnect needs "
                    "@req=N (drop the serve connection every Nth "
                    "request)") from None
            if req < 1:
                raise ValueError(
                    f"fault_spec entry {entry!r}: req must be >= 1")
            faults[slot] = FaultSpec("disconnect", block=req)
        elif kind == "drop_ack":
            # replay-service socket fault (ISSUE 16): the server drops
            # every Nth DATA ack so the windowed producer's cumulative
            # acks must heal the gap via its flush probe — tests feed
            # spec.block into ReplayServiceServer(drop_ack_every=...)
            try:
                every = int(kv.get("every", ""))
            except ValueError:
                raise ValueError(
                    f"fault_spec entry {entry!r}: drop_ack needs "
                    "@every=N (drop every Nth replay-service data "
                    "ack)") from None
            if every < 1:
                raise ValueError(
                    f"fault_spec entry {entry!r}: every must be >= 1")
            faults[slot] = FaultSpec("drop_ack", block=every)
        else:
            try:
                factor = float(kv.get("factor", ""))
            except ValueError:
                raise ValueError(
                    f"fault_spec entry {entry!r}: slow needs @factor=F "
                    "(or the slowxF shorthand)") from None
            if factor <= 1.0:
                raise ValueError(
                    f"fault_spec entry {entry!r}: slow factor must be > 1")
            faults[slot] = FaultSpec("slow", factor=factor)
    return faults


def apply_fault(sink: Callable, fault: FaultSpec,
                on_leave: Optional[Callable[[], None]] = None) -> Callable:
    """Wrap a block sink with one injected fault. Crash raises ChaosFault
    INSTEAD of emitting block N (the worker dies with the block in hand —
    the mid-production death shape); hang wedges there forever (a truly
    unresponsive worker: it ignores stop signals by design, so only the
    watchdog can clear it); slow sleeps (factor-1) x the observed
    inter-emit interval, genuinely stretching block production by
    ``factor`` without guessing at step timings; leave EMITS block N
    then departs — ``on_leave`` (the spawner's membership hook) parks
    the slot for re-adoption before ChaosLeave unwinds the worker, so a
    clean departure is never mistaken for a crash."""
    state = {"emitted": 0, "last": None}

    def faulty_sink(block):
        state["emitted"] += 1
        if fault.kind == "leave" and state["emitted"] >= fault.block:
            out = sink(block)   # the departing worker's last block SHIPS
            del out
            if on_leave is not None:
                on_leave()
            raise ChaosLeave(
                f"injected leave after block emit {state['emitted']}")
        if fault.kind == "crash" and state["emitted"] >= fault.block:
            raise ChaosFault(
                f"injected crash at block emit {state['emitted']}")
        if fault.kind == "hang" and state["emitted"] >= fault.block:
            while True:             # deliberately ignores every stop signal
                time.sleep(0.25)
        if fault.kind == "slow" and state["last"] is not None:
            # cap one sleep at 5s so a long first interval (compile) does
            # not turn the slow fault into an accidental hang
            time.sleep(min((fault.factor - 1.0)
                           * (time.monotonic() - state["last"]), 5.0))
        state["last"] = time.monotonic()
        return sink(block)

    return faulty_sink


class ChaosChannel:
    """Serve-channel fault wrapper (ISSUE 13): the client-side twin of
    ``apply_fault``. ``disconnect@req=N`` drops the connection (an
    explicit lease release + channel reconnect) every Nth request —
    exercising the server's lease/reconnect path with the state-survival
    guarantee under test; ``slow``/``slowxF`` stretches the request
    cadence by F, a laggy client against the micro-batcher's deadline.
    Counts live on the wrapper (``disconnects_injected``) so drills can
    assert the fault actually fired."""

    def __init__(self, inner, fault: FaultSpec):
        self._inner = inner
        self._fault = fault
        self._n = 0
        self._last = None
        self._last_client = None
        self.disconnects_injected = 0

    def _before(self, client_id) -> None:
        self._n += 1
        self._last_client = client_id
        f = self._fault
        if f.kind == "disconnect" and self._n % f.block == 0:
            self._inner.disconnect(client_id)
            self._inner.reconnect()
            self.disconnects_injected += 1
        if f.kind == "slow" and self._last is not None:
            time.sleep(min((f.factor - 1.0)
                           * (time.monotonic() - self._last), 5.0))
        self._last = time.monotonic()

    def request(self, req, timeout: float = 5.0):
        self._before(req.client_id)
        return self._inner.request(req, timeout=timeout)

    def request_many(self, reqs, timeout: float = 5.0):
        if reqs:
            self._before(reqs[0].client_id)
        return self._inner.request_many(reqs, timeout=timeout)

    def reconnect(self) -> None:
        self._inner.reconnect()

    def disconnect(self, client_id) -> None:
        self._inner.disconnect(client_id)

    def close(self) -> None:
        self._inner.close()


def wrap_channel(channel, fault: FaultSpec):
    """Apply a client-side serve fault; non-client kinds pass through."""
    if fault is not None and fault.kind in CLIENT_KINDS:
        return ChaosChannel(channel, fault)
    return channel


# ---------------------------------------------------------------------------
# Chaos phase: injected faults against the real orchestrator (fake env).


def run_chaos(seconds: float = 60.0, actor_mode: str = "process",
              config_overrides: dict = None) -> dict:
    """Train on the fake env with one healthy, one crash-looping, and one
    hanging actor injected; return a JSON-able report of what supervision
    did (the soak's chaos phase, and ``python -m r2d2_tpu.tools.chaos``).

    The crash-looping slot must trip the circuit breaker and park; the
    hanging slot must be watchdog-killed and respawned with backoff; the
    learner must keep training on the healthy slot throughout."""
    from r2d2_tpu.config import Config
    from r2d2_tpu.runtime.orchestrator import train

    overrides = {
        "env.game_name": "Fake",
        "env.frame_height": 24, "env.frame_width": 24, "env.frame_stack": 2,
        "network.hidden_dim": 16, "network.cnn_out_dim": 32,
        "network.conv_layers": ((8, 4, 2), (16, 3, 1)),
        "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
        "sequence.forward_steps": 3,
        "replay.capacity": 800, "replay.block_length": 20,
        "replay.batch_size": 8, "replay.learning_starts": 100,
        "actor.num_actors": 3,
        "actor.fault_spec": "1:crash@block=2;2:hang@block=2",
        "runtime.save_interval": 0, "runtime.log_interval": 2.0,
        "runtime.steps_per_dispatch": 1,
        "runtime.supervise_interval_s": 0.5,
        "runtime.hang_timeout_s": 4.0,
        "runtime.hang_spawn_grace_s": 90.0,
        "runtime.restart_backoff_base_s": 0.5,
        "runtime.restart_backoff_max_s": 4.0,
        # breaker threshold per mode: thread respawns are cheap, so the
        # crash-loop shows one backed-off respawn before parking (trips on
        # the 3rd failure); a process crash cycle costs a full child
        # bring-up (tens of seconds of jax import + env construction), so
        # the default budget only fits two — park on the 2nd failure (the
        # backoff ladder itself is proven by the thread phase and the unit
        # tests)
        "runtime.max_restarts_per_window": 2 if actor_mode == "thread" else 1,
        "runtime.restart_window_s": 300.0,
        "runtime.ingest_stall_timeout_s": 0.0,
    }
    overrides.update(config_overrides or {})
    cfg = Config().replace(**overrides)

    records = []
    t0 = time.time()
    stacks = train(cfg, max_training_steps=10**9, max_seconds=seconds,
                   actor_mode=actor_mode, log_fn=records.append)
    stack = stacks[0]
    report = {
        "metric": "chaos", "actor_mode": actor_mode,
        "duration_s": round(time.time() - t0, 1),
        "fault_spec": cfg.actor.fault_spec,
        "training_steps": stack.learner.training_steps,
        "env_steps": stack.learner.env_steps,
        **stack.health.snapshot(),
        "heartbeat_counts": [int(c) for c in stack.health.board.counts()],
        "records": records[-3:],
    }
    report["verdict"] = {
        "trained_through_faults": stack.learner.training_steps > 0,
        "hang_detected": stack.health.hangs_detected >= 1,
        "restarts_happened": stack.health.restarts >= 1,
    }
    if actor_mode == "thread":
        # required only where the budget guarantees enough crash cycles:
        # a process crash cycle costs a full child bring-up (tens of
        # seconds under CPU contention), so short process-mode runs may
        # legitimately end before the breaker threshold — the trip still
        # shows up in actor_breaker_trips/actor_parked_slots when reached,
        # and the deterministic breaker guarantees live in
        # tests/test_chaos.py
        report["verdict"]["breaker_parked_crash_loop"] = \
            stack.health.breaker_trips >= 1
    return report


# ---------------------------------------------------------------------------
# Serving chaos: the server-kill/restart drill (ISSUE 13).


def run_serve_chaos(seconds: float = 45.0, outage_s: float = 6.0,
                    config_overrides: dict = None) -> dict:
    """Server-kill/restart drill: thread actors act through the central
    policy server (``actor.inference="server"``); mid-run the server loop
    is STOPPED for ``outage_s`` and then restarted against the same
    endpoint. The claims under test: (a) the learner never stalls —
    replay keeps it stepping straight through the outage; (b) clients
    time out, back off on the WorkerHealth ladder, reconnect, and resume
    feeding blocks; (c) ``serve_latency_slo`` fires during the outage
    window and re-arms after recovery."""
    import threading

    from r2d2_tpu.config import Config
    from r2d2_tpu.envs.factory import create_env
    from r2d2_tpu.runtime.orchestrator import PlayerStack

    overrides = {
        "env.game_name": "Fake",
        "env.frame_height": 24, "env.frame_width": 24, "env.frame_stack": 2,
        "network.hidden_dim": 16, "network.cnn_out_dim": 32,
        "network.conv_layers": ((8, 4, 2), (16, 3, 1)),
        "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
        "sequence.forward_steps": 3,
        "replay.capacity": 800, "replay.block_length": 20,
        "replay.batch_size": 8, "replay.learning_starts": 100,
        "actor.num_actors": 2, "actor.inference": "server",
        "serve.max_batch": 8, "serve.deadline_ms": 3.0,
        # timeouts tuned so an OUTAGE-window attempt (~0.5 s) clears the
        # drill's 200 ms SLO bound while healthy requests (~1-5 ms) sit
        # far under it — fire during the outage, re-arm after recovery
        "serve.request_timeout_s": 0.5,
        "serve.max_retry_s": 600.0,
        "telemetry.alerts_serve_p99_ms": 200.0,
        "runtime.save_interval": 0, "runtime.log_interval": 1.5,
        "runtime.steps_per_dispatch": 1,
        "runtime.supervise_interval_s": 1.0,
        "runtime.ingest_stall_timeout_s": 0.0,
    }
    overrides.update(config_overrides or {})
    cfg = Config().replace(**overrides)

    probe = create_env(cfg.env, seed=0)
    action_dim = probe.action_space.n
    probe.close()

    stop = threading.Event()
    stack = PlayerStack(cfg, 0, action_dim)
    records = []
    t0 = time.time()
    outage_at = t0 + max(seconds * 0.35, 8.0)
    restore_at = outage_at + outage_s
    state = "healthy"
    steps_at_kill = steps_at_restore = None
    last_log = last_supervise = t0
    try:
        stack.start_actors_threads(stop)
        while time.time() - t0 < seconds:
            stack.learner.drain(stack.queue)
            if stack.learner.ready:
                stack.learner.step()
            now = time.time()
            if state == "healthy" and now >= outage_at:
                steps_at_kill = stack.learner.training_steps
                stack.serve_server.stop()
                state = "outage"
            elif state == "outage" and now >= restore_at:
                steps_at_restore = stack.learner.training_steps
                stack.restart_serve_server()
                state = "restored"
            if now - last_supervise >= cfg.runtime.supervise_interval_s:
                stack.supervise()
                last_supervise = now
            if now - last_log >= cfg.runtime.log_interval:
                stack.learner.flush_metrics()
                records.append(
                    {"phase": state, **stack.metrics.log(now - last_log)})
                last_log = now
            if not stack.learner.ready:
                time.sleep(0.01)
    finally:
        stop.set()
        stack.close()

    fired = [a["rule"] for r in records
             for a in (r.get("alerts") or {}).get("fired") or []]
    final_active = ((records[-1].get("alerts") or {}).get("active") or []
                    if records else [])
    restored = [r for r in records if r.get("phase") == "restored"]
    reconnects = max((((r.get("serving") or {}).get("clients") or {})
                      .get("reconnects") or 0) for r in records) \
        if records else 0
    resumed = any(((r.get("serving") or {}).get("replies") or 0) > 0
                  for r in restored)
    report = {
        "metric": "serve_chaos",
        "duration_s": round(time.time() - t0, 1),
        "outage_s": outage_s,
        "training_steps": stack.learner.training_steps,
        "steps_at_kill": steps_at_kill,
        "steps_at_restore": steps_at_restore,
        "alerts_fired": fired,
        "final_active": final_active,
        "records": records[-3:],
    }
    report["verdict"] = {
        # the learner kept stepping THROUGH the outage window
        "no_learner_stall": (steps_at_kill is not None
                             and steps_at_restore is not None
                             and steps_at_restore > steps_at_kill),
        "slo_fired": "serve_latency_slo" in fired,
        "slo_rearmed": "serve_latency_slo" not in final_active,
        "clients_resumed": resumed or reconnects > 0,
    }
    return report


def run_serve_fleet_chaos(seconds: float = 45.0, servers: int = 2,
                          config_overrides: dict = None) -> dict:
    """Kill-one-of-N serving-fleet drill (ISSUE 17): thread actors act
    through a SHARDED serving fleet; mid-run one server loop is killed
    abruptly (no handoff). The claims under test: (a) the learner never
    stalls; (b) the supervision pass ADOPTS the victim's orphaned cache
    shards into survivors (leases + op-dedup + hidden state ride along,
    so re-routed streams stay bit-identical — the fast tests pin that
    exactly); (c) clients re-route off the MISROUTED bounces (the shard
    map version moves forward) and resume feeding blocks; (d) the
    serving fleet ends the run one server smaller with every shard
    owned."""
    import threading

    from r2d2_tpu.config import Config
    from r2d2_tpu.envs.factory import create_env
    from r2d2_tpu.runtime.orchestrator import PlayerStack

    overrides = {
        "env.game_name": "Fake",
        "env.frame_height": 24, "env.frame_width": 24, "env.frame_stack": 2,
        "network.hidden_dim": 16, "network.cnn_out_dim": 32,
        "network.conv_layers": ((8, 4, 2), (16, 3, 1)),
        "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
        "sequence.forward_steps": 3,
        "replay.capacity": 800, "replay.block_length": 20,
        "replay.batch_size": 8, "replay.learning_starts": 100,
        # 4 actors spread client ids over the shard ring so every server
        # owns live streams when the victim dies
        "actor.num_actors": 4, "actor.inference": "server",
        "serve.servers": servers, "serve.max_servers": servers,
        "serve.state_shards": 8, "serve.state_slots": 1024,
        "serve.max_batch": 8, "serve.deadline_ms": 3.0,
        "serve.request_timeout_s": 0.5,
        "serve.max_retry_s": 600.0,
        "telemetry.alerts_serve_p99_ms": 200.0,
        "runtime.save_interval": 0, "runtime.log_interval": 1.5,
        "runtime.steps_per_dispatch": 1,
        "runtime.supervise_interval_s": 1.0,
        "runtime.ingest_stall_timeout_s": 0.0,
    }
    overrides.update(config_overrides or {})
    cfg = Config().replace(**overrides)

    probe = create_env(cfg.env, seed=0)
    action_dim = probe.action_space.n
    probe.close()

    stop = threading.Event()
    stack = PlayerStack(cfg, 0, action_dim)
    records = []
    t0 = time.time()
    kill_at = t0 + max(seconds * 0.35, 8.0)
    state = "healthy"
    steps_at_kill = None
    victim = None
    map_v0 = None
    last_log = last_supervise = t0
    try:
        stack.start_actors_threads(stop)
        map_v0 = stack.serve_fleet.shard_map.version
        while time.time() - t0 < seconds:
            stack.learner.drain(stack.queue)
            if stack.learner.ready:
                stack.learner.step()
            now = time.time()
            if state == "healthy" and now >= kill_at:
                steps_at_kill = stack.learner.training_steps
                victim = max(stack.serve_fleet.servers)
                stack.serve_fleet.kill_server(victim)
                state = "killed"
            if now - last_supervise >= cfg.runtime.supervise_interval_s:
                stack.supervise()   # adopts the victim's orphaned shards
                last_supervise = now
            if now - last_log >= cfg.runtime.log_interval:
                stack.learner.flush_metrics()
                records.append(
                    {"phase": state, **stack.metrics.log(now - last_log)})
                last_log = now
            if not stack.learner.ready:
                time.sleep(0.01)
        fleet = stack.serve_fleet
        owned = sorted(g for s in fleet.servers.values()
                       for g in s.cache.owned_shards)
        survivors = sorted(fleet.servers)
        adoptions = fleet.adoptions
        map_v1 = fleet.shard_map.version
        final_steps = stack.learner.training_steps
    finally:
        stop.set()
        stack.close()

    after_kill = [r for r in records if r.get("phase") == "killed"]
    resumed = any(((r.get("serving") or {}).get("replies") or 0) > 0
                  for r in after_kill[1:] or after_kill)
    report = {
        "metric": "serve_fleet_chaos",
        "duration_s": round(time.time() - t0, 1),
        "servers": servers,
        "victim": victim,
        "survivors": survivors,
        "adoptions": adoptions,
        "map_version": [map_v0, map_v1],
        "training_steps": final_steps,
        "steps_at_kill": steps_at_kill,
        "records": records[-3:],
    }
    report["verdict"] = {
        "no_learner_stall": (steps_at_kill is not None
                             and final_steps > steps_at_kill),
        "shards_adopted": adoptions > 0,
        "all_shards_owned": owned == list(range(cfg.serve.state_shards)),
        "fleet_shrunk": (victim is not None
                         and victim not in survivors
                         and len(survivors) == servers - 1),
        "clients_rerouted": map_v1 > map_v0,
        "clients_resumed": resumed,
    }
    return report


# ---------------------------------------------------------------------------
# Membership churn drill (ISSUE 15): live leave + re-join on a running fleet.


def run_churn_drill(seconds: float = 45.0, num_actors: int = 4,
                    leave_frac: float = 0.25,
                    config_overrides: dict = None) -> dict:
    """Elastic-fleet churn drill: thread actors on the fake env with
    ``fleet.elastic`` supervision and the service-routed replay
    (``fleet.replay_shards=2``, lane routing). A quarter of the fleet
    LEAVES mid-training via the grammar's ``leave@block=N`` fault (slot
    parks for re-adoption) and RE-JOINS via ``join@t=S`` (the supervisor
    admits a joiner that adopts the parked slot's lane range + ε slice +
    replay routing). The claims under test:

      * zero learner stalls — training advances in every post-warm-up
        log interval, through the departure window and the re-join;
      * no lane-range overlap — the adopted slot's lanes are exactly
        the departed worker's (membership.assert_no_overlap);
      * provenance — every block row in replay shard s carries a lane
        stamp with ``lane % num_shards == s`` (the PR-10 stamps prove
        adopted slots route into the correct shards)."""
    import threading

    import numpy as np

    from r2d2_tpu.config import Config
    from r2d2_tpu.envs.factory import create_env
    from r2d2_tpu.runtime.orchestrator import PlayerStack

    n_leave = max(1, int(num_actors * leave_frac))
    join_at = max(seconds * 0.55, 12.0)
    spec_parts = []
    for s in range(n_leave):
        spec_parts.append(f"{s}:leave@block={3 + s}")
        spec_parts.append(f"{s}:join@t={join_at + 2.0 * s:.1f}")
    overrides = {
        "env.game_name": "Fake",
        "env.frame_height": 24, "env.frame_width": 24, "env.frame_stack": 2,
        "network.hidden_dim": 16, "network.cnn_out_dim": 32,
        "network.conv_layers": ((8, 4, 2), (16, 3, 1)),
        "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
        "sequence.forward_steps": 3,
        "replay.capacity": 800, "replay.block_length": 20,
        "replay.batch_size": 8, "replay.learning_starts": 100,
        "actor.num_actors": num_actors,
        "actor.fault_spec": ";".join(spec_parts),
        "fleet.elastic": True,
        "fleet.replay_shards": 2,
        "fleet.replay_route": "lane",
        "runtime.save_interval": 0, "runtime.log_interval": 2.0,
        "runtime.steps_per_dispatch": 1,
        "runtime.supervise_interval_s": 0.5,
        "runtime.ingest_stall_timeout_s": 0.0,
    }
    overrides.update(config_overrides or {})
    cfg = Config().replace(**overrides)

    probe = create_env(cfg.env, seed=0)
    action_dim = probe.action_space.n
    probe.close()

    stop = threading.Event()
    stack = PlayerStack(cfg, 0, action_dim)
    records = []
    t0 = time.time()
    steps_at_leave = steps_at_join = None
    last_log = last_supervise = t0
    try:
        stack.start_actors_threads(stop)
        while time.time() - t0 < seconds:
            stack.learner.drain(stack.queue)
            if stack.learner.ready:
                stack.learner.step()
            now = time.time()
            if now - last_supervise >= cfg.runtime.supervise_interval_s:
                stack.supervise()
                last_supervise = now
            if steps_at_leave is None and stack.membership.leaves >= n_leave:
                steps_at_leave = stack.learner.training_steps
            if steps_at_join is None and stack.membership.joins >= n_leave:
                steps_at_join = stack.learner.training_steps
            if now - last_log >= cfg.runtime.log_interval:
                stack.learner.flush_metrics()
                records.append(stack.metrics.log(now - last_log))
                last_log = now
            if not stack.learner.ready:
                time.sleep(0.01)
        stack.membership.assert_no_overlap()
        # provenance (PR-10 lane stamps through the service's lane
        # routing): every live row of shard s must carry lane % S == s
        shard_lanes = []
        routed_ok = True
        service = stack.learner.service
        if service is not None:
            for shard in service.shards:
                lanes = np.asarray(shard.state.lane)
                live = lanes[lanes >= 0]
                shard_lanes.append(sorted(set(int(x) for x in live)))
                if live.size and not bool(np.all(
                        live % service.num_shards == shard.index)):
                    routed_ok = False
        membership = stack.membership.snapshot(stack.heartbeats.ages(),
                                               orphan_horizon_s=0.0)
    finally:
        stop.set()
        stack.close()

    trained = [r for r in records if r.get("training_speed")]
    # zero-stall: once training started, EVERY interval advanced (the
    # churn window included)
    started = False
    stalled_intervals = 0
    for r in records:
        speed = r.get("training_speed") or 0.0
        if speed > 0:
            started = True
        elif started:
            stalled_intervals += 1
    report = {
        "metric": "churn_drill",
        "duration_s": round(time.time() - t0, 1),
        "fault_spec": cfg.actor.fault_spec,
        "num_actors": num_actors, "left_and_rejoined": n_leave,
        "training_steps": records[-1]["training_steps"] if records else 0,
        "steps_at_leave": steps_at_leave,
        "steps_at_join": steps_at_join,
        "stalled_intervals": stalled_intervals,
        "membership": membership,
        "shard_lanes": shard_lanes,
        "records": records[-3:],
    }
    report["verdict"] = {
        "left": membership["leaves"] >= n_leave,
        "rejoined": membership["joins"] >= n_leave,
        "zero_learner_stalls": (bool(trained) and stalled_intervals == 0
                                and steps_at_join is not None
                                and steps_at_leave is not None
                                and steps_at_join > steps_at_leave),
        "no_lane_overlap": True,    # assert_no_overlap raised otherwise
        "shards_routed_by_lane": routed_ok,
    }
    return report


# ---------------------------------------------------------------------------
# Crash-recovery kill drills (ISSUE 18): SIGKILL the learner / the
# standalone replay service mid-run and assert the recovery plane puts
# the run back together.


def _read_jsonl(path: str) -> list:
    """Best-effort metrics reader: skips partial trailing lines (a
    writer mid-append) and anything unparseable."""
    import json
    import os
    rows = []
    if not os.path.exists(path):
        return rows
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return rows


def _read_pid(path: str):
    import os
    try:
        with open(path) as f:
            pid = int(f.read().strip())
    except (OSError, ValueError):
        return None
    try:
        os.kill(pid, 0)
    except (ProcessLookupError, PermissionError, OSError):
        return None
    return pid


def run_kill_learner_drill(seconds: float = 150.0,
                           config_overrides: dict = None) -> dict:
    """Learner kill drill (ISSUE 18 tentpole d): train on the fake env
    under ``runtime.auto_resume`` with the snapshot plane on, SIGKILL
    the training child mid-run (via ``{save_dir}/learner.pid``), and
    assert the supervisor relaunched it, that training resumed PAST the
    kill point from the newest checkpoint, that the replay buffer came
    back from the durable snapshot (``recovery.restores``), that the
    restored contents cover everything durable at the kill (loss ≤ one
    snapshot interval of commits), and that the restart did not set off
    an actor crash storm (no breaker trips, no parked slots, exactly
    one supervisor restart)."""
    import os
    import signal
    import tempfile
    import threading

    from r2d2_tpu.config import Config
    from r2d2_tpu.replay.snapshot import read_manifest
    from r2d2_tpu.runtime.checkpoint import latest_checkpoint
    from r2d2_tpu.runtime.supervisor import _pid_path, supervise_train

    save_dir = tempfile.mkdtemp(prefix="r2d2_kill_learner_")
    overrides = {
        "env.game_name": "Fake",
        "env.frame_height": 24, "env.frame_width": 24, "env.frame_stack": 2,
        "network.hidden_dim": 16, "network.cnn_out_dim": 32,
        "network.conv_layers": ((8, 4, 2), (16, 3, 1)),
        "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
        "sequence.forward_steps": 3,
        "replay.capacity": 800, "replay.block_length": 20,
        "replay.batch_size": 8, "replay.learning_starts": 100,
        "actor.num_actors": 2,
        "telemetry.enabled": True,
        "runtime.save_dir": save_dir,
        "runtime.save_interval": 25,
        "runtime.snapshot_interval": 25,
        "runtime.auto_resume": True,
        "runtime.log_interval": 1.0,
        "runtime.steps_per_dispatch": 1,
        # a tight ladder so the relaunch is fast, with a window wide
        # enough that the drill's single kill can never trip the breaker
        "runtime.restart_backoff_base_s": 0.2,
        "runtime.restart_backoff_max_s": 1.0,
        "runtime.max_restarts_per_window": 3,
        "runtime.restart_window_s": 600.0,
    }
    overrides.update(config_overrides or {})
    cfg = Config().replace(**overrides)
    game = cfg.env.game_name

    pid_file = _pid_path(save_dir)
    metrics_path = os.path.join(save_dir, "metrics_player0.jsonl")
    holder = {"restarts": None, "error": None}

    def _run():
        try:
            # thread-mode actors: they die WITH the killed child, so the
            # SIGKILL cannot orphan an actor fleet
            holder["restarts"] = supervise_train(
                cfg, actor_mode="thread", max_seconds=seconds * 2 + 120)
        except Exception as e:   # breaker trip surfaces in the verdict
            holder["error"] = repr(e)

    sup = threading.Thread(target=_run, name="drill-supervisor", daemon=True)
    t0 = time.time()
    sup.start()

    def _wait(pred, timeout):
        deadline = time.time() + timeout
        while time.time() < deadline and sup.is_alive():
            if pred():
                return True
            time.sleep(0.2)
        return pred()

    pid0 = steps_at_kill = adds_at_kill = None
    killed = False
    ready = _wait(
        lambda: (_read_pid(pid_file) is not None
                 and latest_checkpoint(save_dir, game, 0) is not None
                 and (read_manifest(save_dir, 0) or {}).get("total_adds", 0) > 0
                 and (_read_jsonl(metrics_path) or [{}])[-1]
                     .get("training_steps", 0) > 0),
        timeout=seconds)
    if ready:
        pid0 = _read_pid(pid_file)
        rows = _read_jsonl(metrics_path)
        rows_at_kill = len(rows)
        steps_at_kill = rows[-1].get("training_steps", 0)
        adds_at_kill = read_manifest(save_dir, 0)["total_adds"]
        os.kill(pid0, signal.SIGKILL)
        killed = True

        def _recovered():
            pid = _read_pid(pid_file)
            if pid is None or pid == pid0:
                return False
            fresh = _read_jsonl(metrics_path)[rows_at_kill:]
            return any(((r.get("recovery") or {}).get("restores") or 0) >= 1
                       for r in fresh) and any(
                r.get("training_steps", 0) > steps_at_kill for r in fresh)
        _wait(_recovered, timeout=seconds)

    # clean stop: SIGTERM the CURRENT child — its clean-stop path exits 0
    # and the supervisor breaks without relaunching
    for _ in range(3):
        if not sup.is_alive():
            break
        pid = _read_pid(pid_file)
        if pid is not None:
            try:
                os.kill(pid, signal.SIGTERM)
            except (ProcessLookupError, OSError):
                pass
        sup.join(timeout=30.0)
    sup.join(timeout=30.0)

    rows = _read_jsonl(metrics_path)
    post = rows[rows_at_kill:] if killed else []
    recovery_rows = [r.get("recovery") for r in post if r.get("recovery")]
    restored_blocks = max(
        (r.get("restored_blocks") or 0 for r in recovery_rows), default=0)
    restarts_seen = max(
        ((r.get("supervisor") or {}).get("restarts") or 0
         for r in recovery_rows), default=0)
    final = rows[-1] if rows else {}
    final_steps = final.get("training_steps", 0)

    report = {
        "metric": "kill_learner_drill",
        "duration_s": round(time.time() - t0, 1),
        "save_dir": save_dir,
        "killed_pid": pid0,
        "steps_at_kill": steps_at_kill,
        "snapshot_adds_at_kill": adds_at_kill,
        "restored_blocks": restored_blocks,
        "supervisor_restarts": holder["restarts"],
        "supervisor_error": holder["error"],
        "training_steps": final_steps,
        "records": rows[-3:],
    }
    report["verdict"] = {
        "killed": killed,
        "relaunched": restarts_seen >= 1,
        "resumed_training": (killed and steps_at_kill is not None
                             and final_steps > steps_at_kill),
        "replay_restored": any(
            (r.get("restores") or 0) >= 1 for r in recovery_rows),
        # everything durable at the kill came back: the loss is bounded
        # by the commits since the last snapshot — one interval at most
        "bounded_loss": (killed and restored_blocks >= (adds_at_kill or 0)
                         and (adds_at_kill or 0) > 0),
        "no_crash_storm": (holder["error"] is None
                           and restarts_seen == 1
                           and final.get("actor_breaker_trips", 0) == 0
                           and final.get("actor_parked_slots", 0) == 0),
    }
    return report


def _service_child(cfg_dict: dict) -> None:
    """Spawn target for one standalone replay-service incarnation
    (module-level: the ``spawn`` start method pickles by reference)."""
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from r2d2_tpu.config import Config
    from r2d2_tpu.fleet.service_main import run_replay_service
    run_replay_service(Config.from_dict(cfg_dict), 0)


def _synth_blocks(cfg, n: int, seed: int = 0) -> list:
    """A pool of well-formed fake-env blocks for the service drill —
    the same LocalBuffer path the actors use, so the wire shapes match
    the service's spec exactly."""
    import numpy as np

    from r2d2_tpu.actor.local_buffer import LocalBuffer
    from r2d2_tpu.replay.structs import ReplaySpec

    spec = ReplaySpec.from_config(cfg)
    action_dim = 4
    rng = np.random.default_rng(seed)
    buf = LocalBuffer(spec, action_dim, gamma=0.99)
    buf.reset(np.zeros((spec.frame_height, spec.frame_width), np.uint8))
    blocks = []
    t = 0
    for _ in range(n):
        for i in range(spec.block_length):
            obs = np.full((spec.frame_height, spec.frame_width),
                          (t + i) % 250, np.uint8)
            q = rng.normal(size=action_dim).astype(np.float32)
            hidden = rng.normal(size=(2, spec.hidden_dim)).astype(np.float32)
            buf.add((t + i) % action_dim, float((t + i) % 3), obs, q, hidden)
        t += spec.block_length
        blocks.append(buf.finish(
            last_qval=rng.normal(size=action_dim).astype(np.float32)))
    return blocks


def run_kill_replay_service_drill(seconds: float = 120.0,
                                  config_overrides: dict = None) -> dict:
    """Replay-service kill drill (ISSUE 18 tentpole d): host the
    standalone service (fleet/service_main.py) in its own process,
    stream blocks at it through a windowed RemoteReplayProducer,
    SIGKILL the service mid-ingest, restart it, and assert:

      * the producer SURVIVED the dead socket — reconnect ladder +
        unacked-tail replay, no exception, every sent block acked;
      * the restarted service RESTORED the durable snapshot (committed
        blocks are monotone across the kill);
      * the loss is BOUNDED: at most one snapshot interval of commits
        (plus the in-flight window) went down with the process."""
    import multiprocessing as mp
    import os
    import socket as socket_mod
    import tempfile
    import threading

    from r2d2_tpu.config import Config
    from r2d2_tpu.fleet.replay_service import RemoteReplayProducer
    from r2d2_tpu.replay.snapshot import read_manifest

    save_dir = tempfile.mkdtemp(prefix="r2d2_kill_service_")
    s = socket_mod.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    interval = 8
    overrides = {
        "env.game_name": "Fake",
        "env.frame_height": 24, "env.frame_width": 24, "env.frame_stack": 2,
        "network.hidden_dim": 16, "network.cnn_out_dim": 32,
        "network.conv_layers": ((8, 4, 2), (16, 3, 1)),
        "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
        "sequence.forward_steps": 3,
        "replay.capacity": 800, "replay.block_length": 20,
        "replay.batch_size": 8, "replay.learning_starts": 100,
        "fleet.replay_shards": 2,
        "fleet.service_host": "127.0.0.1",
        "fleet.service_port": port,
        "runtime.save_dir": save_dir,
        "runtime.snapshot_interval": interval,
    }
    overrides.update(config_overrides or {})
    cfg = Config().replace(**overrides)
    cfg_dict = cfg.to_dict()
    interval = cfg.runtime.snapshot_interval

    ctx = mp.get_context("spawn")
    t0 = time.time()
    child = ctx.Process(target=_service_child, args=(cfg_dict,),
                        name="replay-service-0")
    child.start()
    pool = _synth_blocks(cfg, 12)
    group = 2
    window = 4
    # the eager dial + _recover both ride this ladder: wide enough to
    # cover a full spawn+jax import of the replacement service
    producer = RemoteReplayProducer(
        "127.0.0.1", port, window=window, connect_retries=120,
        backoff_base_s=0.1, backoff_max_s=1.0, eager_connect=True)

    state = {"sent": 0, "error": None}
    stop_send = threading.Event()

    def _sender():
        i = 0
        try:
            while not stop_send.is_set():
                producer.add_blocks(
                    [pool[(i + j) % len(pool)] for j in range(group)])
                state["sent"] += group
                i += group
                time.sleep(0.02)
        except Exception as e:
            state["error"] = repr(e)

    sender = threading.Thread(target=_sender, name="drill-producer",
                              daemon=True)
    sender.start()

    def _wait(pred, timeout):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if pred():
                return True
            time.sleep(0.1)
        return pred()

    killed = restarted = False
    adds_at_kill = sent_at_kill = None
    child2 = None
    final_manifest = None
    try:
        # phase 1: ingest until the service committed + snapshotted
        ready = _wait(
            lambda: ((read_manifest(save_dir, 0) or {})
                     .get("total_adds", 0) >= interval
                     and state["sent"] >= 2 * interval
                     and state["error"] is None),
            timeout=seconds)
        if ready:
            manifest = read_manifest(save_dir, 0)
            adds_at_kill = manifest["total_adds"]
            sent_at_kill = state["sent"]
            child.kill()                      # SIGKILL, mid-ingest
            child.join(timeout=30.0)
            killed = True
            # phase 2: restart; the producer's ladder rides the outage
            child2 = ctx.Process(target=_service_child, args=(cfg_dict,),
                                 name="replay-service-1")
            child2.start()
            restarted = _wait(
                lambda: (state["sent"] > sent_at_kill + 2 * interval
                         and state["error"] is None),
                timeout=seconds)
    finally:
        stop_send.set()
        sender.join(timeout=60.0)
        try:
            if state["error"] is None:
                producer.flush()
        except OSError as e:
            state["error"] = repr(e)
        producer.close()
        # clean stop: SIGTERM → final synchronous snapshot on close()
        for c in (child, child2):
            if c is not None and c.is_alive():
                c.terminate()
                c.join(timeout=60.0)
                if c.is_alive():
                    c.kill()
                    c.join(timeout=10.0)
        final_manifest = read_manifest(save_dir, 0)

    final_adds = (final_manifest or {}).get("total_adds", 0)
    # duplicates from the ack-replay tail COUNT as adds (idempotent
    # overwrite), so sent - adds can go negative; clamp
    lost_est = max(0, state["sent"] - final_adds)
    report = {
        "metric": "kill_replay_service_drill",
        "duration_s": round(time.time() - t0, 1),
        "save_dir": save_dir,
        "blocks_sent": state["sent"],
        "blocks_acked": producer.blocks_acked,
        "blocks_resent": producer.blocks_resent,
        "reconnects": producer.reconnects,
        "producer_error": state["error"],
        "snapshot_adds_at_kill": adds_at_kill,
        "final_total_adds": final_adds,
        "lost_blocks_est": lost_est,
        "loss_bound": (interval + window * group) if killed else None,
    }
    report["verdict"] = {
        "killed": killed,
        "producer_survived": (killed and state["error"] is None
                              and producer.reconnects >= 1),
        "all_sent_acked": (state["sent"] > 0
                           and producer.blocks_acked == state["sent"]),
        "service_restored": (restarted
                             and final_adds >= (adds_at_kill or 0)
                             and (adds_at_kill or 0) > 0),
        "bounded_loss": (killed
                         and lost_est <= interval + window * group),
    }
    return report


def _trees_equal(a, b) -> bool:
    """Bit-identity for two param pytrees: same structure, same dtypes,
    same bytes — the rollback contract is EXACT restoration, so a
    tolerance would hide the very corruption the drill exists to catch."""
    import jax
    import numpy as np
    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    return (ta == tb and len(la) == len(lb)
            and all(np.asarray(x).dtype == np.asarray(y).dtype
                    and np.array_equal(np.asarray(x), np.asarray(y))
                    for x, y in zip(la, lb)))


def _perturb_head(params, factor: float):
    """Copy of a param tree with the Q-head's OUTPUT layer (adv_out)
    scaled by ``factor``. A positive factor preserves every argmax
    exactly (q scales monotonically, dueling or not — the value stream
    is action-independent), so it is the HEALTHY candidate: different
    bytes, identical greedy policy. A negative factor flips argmax to
    argmin — the CORRUPTED candidate the gates must refuse."""
    import copy

    import numpy as np
    out = copy.deepcopy(params)
    head = out["params"]["head"]["adv_out"]
    for k in head:
        head[k] = np.asarray(head[k]) * np.float32(factor)
    return out


def run_promotion_drill(seconds: float = 120.0,
                        config_overrides: dict = None) -> dict:
    """Gated canary promotion drill (ISSUE 20 tentpole c): prove on REAL
    serving + fan-out plumbing that

      * a CORRUPTED candidate (perturbed head weights) staged as a canary
        is caught by shadow scoring on mirrored live traffic, fires the
        ``canary_divergence`` alert EXACTLY ONCE, and is refused without
        the root store ever publishing;
      * a HEALTHY candidate clears every gate (eval return through the
        real ``evaluate_scenarios`` rollouts, calibration, shadow) and
        promotes fleet-wide via ONE root publish — every fan-out consumer
        adopts the candidate bundle;
      * one-command ``rollback()`` re-publishes the retained previous
        bundle BIT-IDENTICALLY (stamp and weight-tree equality asserted).

    Everything runs in-proc: two PolicyServers (live + candidate) over
    InprocEndpoints, a RoutingChannel with the ShadowScorer installed as
    its mirror tap, an InProcWeightStore under a FanoutTree, and the
    in-run AlertEngine evaluating real ``quality`` record blocks."""
    import tempfile

    import jax
    import numpy as np

    from r2d2_tpu.cli.evaluate import evaluate_scenarios
    from r2d2_tpu.config import Config
    from r2d2_tpu.fleet.fanout import FanoutTree
    from r2d2_tpu.fleet.promotion import PromotionManager, ShadowScorer
    from r2d2_tpu.models.network import NetworkApply
    from r2d2_tpu.runtime.checkpoint import save_checkpoint
    from r2d2_tpu.runtime.weights import InProcWeightStore
    from r2d2_tpu.serve import InprocEndpoint, PolicyServer, RemotePolicy
    from r2d2_tpu.serve.router import RoutingChannel, ShardMap
    from r2d2_tpu.telemetry import AlertEngine, default_rules
    from r2d2_tpu.telemetry.quality import (QualityEvaluator, QualityStats,
                                            make_calibration_feed)

    save_dir = tempfile.mkdtemp(prefix="r2d2_promotion_")
    overrides = {
        "env.game_name": "Fake",
        "env.frame_height": 24, "env.frame_width": 24, "env.frame_stack": 2,
        "network.hidden_dim": 16, "network.cnn_out_dim": 32,
        "network.conv_layers": ((8, 4, 2), (16, 3, 1)),
        "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
        "sequence.forward_steps": 3,
        "replay.capacity": 800, "replay.block_length": 20,
        "replay.batch_size": 8, "replay.learning_starts": 100,
        "serve.max_batch": 4, "serve.deadline_ms": 2.0,
        "serve.shadow_sample_rate": 1.0,
        "fleet.promotion_min_shadow": 16,
        "telemetry.enabled": True, "telemetry.quality_enabled": True,
        "runtime.save_dir": save_dir, "runtime.save_interval": 0,
    }
    overrides.update(config_overrides or {})
    cfg = Config().replace(**overrides)
    t0 = time.time()

    # -- the three bundles: live, healthy candidate, corrupted candidate --
    action_dim = 6                    # JaxFakeEnv's action space
    net = NetworkApply(action_dim, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    live_params = jax.device_get(net.init(jax.random.PRNGKey(0)))
    healthy = _perturb_head(live_params, 1.001)   # same argmax, new bytes
    corrupt = _perturb_head(live_params, -1.0)    # argmax -> argmin

    # checkpoints so the eval gate runs the REAL rollout machinery; the
    # candidate saves under player 1 so the live evaluator's
    # list_checkpoints poll (player 0) only ever sees the live bundle
    opt_stub = {"stub": np.zeros(1, np.float32)}
    live_ckpt = save_checkpoint(save_dir, cfg.env.game_name, 1, 0,
                                live_params, opt_stub, live_params,
                                step=100, env_steps=4000,
                                config_json=cfg.to_json())
    cand_ckpt = save_checkpoint(save_dir, cfg.env.game_name, 1, 1,
                                healthy, opt_stub, healthy,
                                step=200, env_steps=8000,
                                config_json=cfg.to_json())

    # -- distribution plane: root store + fan-out tree (8 consumers) --
    store = InProcWeightStore(live_params)
    fanout = FanoutTree(store, n_consumers=8, degree=2)
    fanout.pump()                                 # seed relays from root
    stats = QualityStats()
    mgr = PromotionManager(cfg.fleet, store, fanout=fanout, stats=stats,
                           save_dir=save_dir)
    engine = AlertEngine(default_rules(cfg.telemetry))
    fired: list = []                              # every firing, in order

    def observe_interval():
        record = {"quality": stats.interval_block()}
        fired.extend(a["rule"] for a in engine.evaluate(record)["fired"])
        return record["quality"]

    # -- serving plane: live server behind a router, candidates shadowed --
    ep_live = InprocEndpoint()
    live_srv = PolicyServer(cfg, net, live_params, endpoint=ep_live).start()
    smap = ShardMap(4, [0] * 4)

    def drive_traffic(chan, steps: int, seed: int) -> None:
        rng = np.random.default_rng(seed)
        # a fresh client identity per phase: a reused id would collide
        # with the previous phase's op-dedup bookkeeping in the cache
        policy = RemotePolicy(chan, net.action_dim, 0.05, seed=seed,
                              client_id=seed, timeout_s=30.0)
        policy.observe_reset(rng.integers(
            0, 255, (cfg.env.frame_height, cfg.env.frame_width), np.uint8))
        for _ in range(steps):
            action, _, _ = policy.act()
            policy.observe(rng.integers(
                0, 255, (cfg.env.frame_height, cfg.env.frame_width),
                np.uint8), action)
        policy.close()

    def shadow_against(params, seed: int):
        """Serve ``params`` as the candidate, mirror live traffic at it,
        and return (scorer, divergence over this phase's requests). Each
        phase gets its own router (the policy's close() closes the
        channel) — the mirror tap rides that router."""
        ep = InprocEndpoint()
        srv = PolicyServer(cfg, net, params, endpoint=ep).start()
        scorer = ShadowScorer(ep.connect(), stats,
                              sample_rate=cfg.serve.shadow_sample_rate,
                              timeout_s=30.0, seed=seed)
        chan = RoutingChannel({0: ep_live.connect()}, smap)
        chan.set_mirror(scorer.mirror)
        try:
            drive_traffic(chan, 40, seed)
            scorer.process_pending()
        finally:
            srv.stop()
        return scorer, scorer.divergence()

    report = {"metric": "promotion_drill", "save_dir": save_dir}
    verdict = {}
    evaluator = None
    try:
        # -- eval gate evidence: continuous evaluator on the live ckpt
        # (the real background path: list_checkpoints poll + served
        # rollouts), candidate scored by the same machinery directly --
        evaluator = QualityEvaluator(cfg, 0, stats, rounds=2, clients=2,
                                     serve=True,
                                     stamp_fn=lambda: store.publish_count)
        assert evaluator.run_once() is not None
        seed = cfg.runtime.seed + 777         # the evaluator's eval seed
        live_eval = evaluate_scenarios(cfg, live_ckpt, 2, seed=seed)
        cand_eval = evaluate_scenarios(cfg, cand_ckpt, 2, seed=seed)
        live_return = live_eval["mean_return"]
        cand_return = cand_eval["mean_return"]
        # calibration signal through the LocalBuffer-tap plumbing
        feed = make_calibration_feed(
            stats, gamma=cfg.optim.gamma,
            n_steps=cfg.sequence.forward_steps,
            stamp_fn=lambda: store.publish_count)
        rng = np.random.default_rng(7)
        feed(rng.normal(size=(21, action_dim)).astype(np.float32),
             rng.normal(size=(20,)).astype(np.float32))

        # -- phase 1: the corrupted candidate must be refused --
        staged1 = mgr.stage(corrupt)
        canary_slots = staged1["canary_consumers"]
        canary_live = all(_trees_equal(
            fanout.endpoints(c)[2](), corrupt) for c in canary_slots)
        uncovered = [c for c in range(8) if c not in canary_slots]
        uncovered_live = all(_trees_equal(
            fanout.endpoints(c)[2](), live_params) for c in uncovered)
        scorer1, div1 = shadow_against(corrupt, seed=11)
        q1 = observe_interval()               # fires canary_divergence
        ok1, gates1 = mgr.decide(
            candidate_return=cand_return, live_return=live_return,
            calibration_gap=q1["calibration"]["gap_mean"],
            shadow_divergence=div1, shadow_requests=scorer1.scored)
        if not ok1:
            mgr.refuse(gates1)
        refused_block = mgr.block()
        # canary slice back on the live bundle, root untouched
        canary_cleared = all(_trees_equal(
            fanout.endpoints(c)[2](), live_params) for c in canary_slots)
        root_untouched = (store.publish_count == 1
                          and mgr.root_publishes == 0)
        observe_interval()                    # no re-fire while refused

        # -- phase 2: the healthy candidate must promote fleet-wide --
        scorer2, div2 = shadow_against(healthy, seed=23)
        q2 = observe_interval()               # divergence ~0: rule re-arms
        staged2 = mgr.stage(healthy, stamp=cand_eval["step"])
        ok2, gates2 = mgr.decide(
            candidate_return=cand_return, live_return=live_return,
            calibration_gap=q2["calibration"]["gap_mean"],
            shadow_divergence=div2, shadow_requests=scorer2.scored)
        publishes_before = (store.publish_count, mgr.root_publishes)
        promoted_stamp = mgr.promote() if ok2 else None
        one_root_publish = (
            store.publish_count == publishes_before[0] + 1
            and mgr.root_publishes == publishes_before[1] + 1)
        fleet_adopted = all(_trees_equal(
            fanout.endpoints(c)[2](), healthy) for c in range(8))
        observe_interval()

        # -- phase 3: one-command rollback, bit-identical --
        rb_stamp = mgr.rollback()
        restored = store.current()
        rollback_identical = (
            rb_stamp == staged2["previous_stamp"]
            and _trees_equal(restored, live_params)
            and all(_trees_equal(fanout.endpoints(c)[2](), live_params)
                    for c in range(8)))
        final_q = observe_interval()

        report.update({
            "duration_s": round(time.time() - t0, 1),
            "live_return": live_return,
            "candidate_return": cand_return,
            "corrupt_divergence": div1,
            "healthy_divergence": div2,
            "corrupt_gates": gates1,
            "healthy_gates": gates2,
            "canary_consumers": canary_slots,
            "promoted_stamp": promoted_stamp,
            "rolled_back_to_stamp": rb_stamp,
            "alerts_fired": fired,
            "final_quality": final_q,
        })
        verdict = {
            "eval_gate_real": (live_eval["step"] == 100
                               and cand_eval["step"] == 200
                               and gates2["eval_return"]["ok"]),
            "canary_scoped": (len(canary_slots) >= 2 and canary_live
                              and uncovered_live),
            "corrupt_refused": (not ok1
                                and not gates1["shadow"]["ok"]
                                and refused_block["state"] == "refused"
                                and root_untouched and canary_cleared),
            "canary_divergence_fired_once": (
                fired.count("canary_divergence") == 1),
            "healthy_promoted": (ok2
                                 and promoted_stamp
                                 == staged2["candidate_stamp"]),
            "one_root_publish": one_root_publish,
            "fleet_adopted": fleet_adopted,
            "rollback_bit_identical": rollback_identical,
        }
    finally:
        if evaluator is not None:
            evaluator.stop()
        live_srv.stop()
    report["verdict"] = verdict
    return report


def main(argv=None) -> int:
    import argparse
    import json
    import sys

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seconds", type=float, default=60.0)
    p.add_argument("--actor-mode", choices=("thread", "process"),
                   default="process")
    p.add_argument("--serve", action="store_true",
                   help="run the ISSUE-13 server-kill/restart drill "
                        "instead of the worker-fault phase")
    p.add_argument("--churn", action="store_true",
                   help="run the ISSUE-15 membership churn drill "
                        "(leave 25%% of the fleet mid-training, re-join "
                        "it, assert zero learner stalls + shard-routing "
                        "provenance) instead of the worker-fault phase")
    p.add_argument("--serve-fleet", action="store_true",
                   help="run the ISSUE-17 kill-one-of-N serving-fleet "
                        "drill: survivors adopt the victim's cache "
                        "shards, clients re-route, the learner never "
                        "stalls")
    p.add_argument("--kill-learner", action="store_true",
                   help="run the ISSUE-18 learner kill drill: SIGKILL "
                        "the training child mid-run under "
                        "runtime.auto_resume, assert the supervisor "
                        "relaunched it past the kill point with the "
                        "replay snapshot restored (loss ≤ one snapshot "
                        "interval) and no actor crash storm")
    p.add_argument("--kill-replay-service", action="store_true",
                   help="run the ISSUE-18 replay-service kill drill: "
                        "SIGKILL the standalone service mid-ingest, "
                        "restart it, assert producer reconnect + "
                        "unacked-tail replay and a bounded-loss "
                        "snapshot restore")
    p.add_argument("--promotion", action="store_true",
                   help="run the ISSUE-20 gated-canary promotion drill: "
                        "a corrupted candidate (perturbed head weights) "
                        "is refused with canary_divergence fired exactly "
                        "once; a healthy candidate promotes fleet-wide "
                        "via ONE root publish; rollback restores the "
                        "previous bundle bit-identically")
    p.add_argument("--servers", type=int, default=2,
                   help="--serve-fleet: fleet width before the kill")
    p.add_argument("--outage-seconds", type=float, default=6.0,
                   help="--serve: how long the policy server stays down")
    p.add_argument("--override", action="append", default=[],
                   help="dotted config override key=value (repeatable)")
    args = p.parse_args(argv)
    overrides = {}
    for ov in args.override:
        k, _, v = ov.partition("=")
        try:
            overrides[k] = json.loads(v)
        except (json.JSONDecodeError, ValueError):
            overrides[k] = v
    if args.promotion:
        out = run_promotion_drill(args.seconds, config_overrides=overrides)
    elif args.kill_learner:
        out = run_kill_learner_drill(max(args.seconds, 120.0),
                                     config_overrides=overrides)
    elif args.kill_replay_service:
        out = run_kill_replay_service_drill(max(args.seconds, 90.0),
                                            config_overrides=overrides)
    elif args.churn:
        out = run_churn_drill(args.seconds, config_overrides=overrides)
    elif args.serve_fleet:
        out = run_serve_fleet_chaos(args.seconds, args.servers, overrides)
    elif args.serve:
        out = run_serve_chaos(args.seconds, args.outage_seconds, overrides)
    else:
        out = run_chaos(args.seconds, args.actor_mode, overrides)
    print(json.dumps(out))
    ok = all(out["verdict"].values())
    print(f"chaos: verdict={'PASS' if ok else 'FAIL'} {out['verdict']}",
          file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    import sys
    sys.exit(main())
