"""End-to-end actors→learner throughput benchmark.

Everything measured before this tool was learner-only (bench.py: fused-step
seq-updates/s on synthetic batches; tools/soak.py: device-side ring
behavior). This tool measures the SYSTEM: how fast experience is generated
and how fast it is consumed, simultaneously — the reference's two logged
speeds, 'buffer update speed' and 'training speed'
(/root/reference/worker.py:222,229) — plus an actor-only scalar-vs-vector
sweep that quantifies the ``actor.envs_per_actor`` batching win on this
host (VERDICT "Next round" #3: does the feeder side become the wall?).

Phases:

  1. **Actor sweep** (in-process, no learner): one actor worker on the fake
     env at each requested ``envs_per_actor`` (1 = the legacy scalar loop,
     N>1 = the vectorized loop's single jitted (N, 1) forward), timed after
     a compile warm-up. Reports env-steps/s per cell and the speedup over
     the scalar loop — the Podracer-style batching measurement (arxiv
     2104.06272, 1907.08467).
  2. **End-to-end run** (optional, ``--e2e-seconds > 0``): the REAL system —
     process-mode vector actors feeding the real learner through the shm
     block ring — via orchestrator.train, reporting steady-state env-steps/s
     and learner updates/s (and seq-updates/s = updates/s × batch) from the
     TrainMetrics records.
  3. **Ingestion A/B** (default when the e2e phase runs, ``--ingest-ab``):
     the e2e run twice — batched+pipelined replay ingestion
     (``replay.ingest_batch_blocks = K``: stacked feeder drains, one
     ``replay_add_many`` dispatch per K blocks, background stager) vs the
     legacy per-block path — with blocks/s ingested, drain latency, and
     rate-limiter pause time from the ingestion counters, in one artifact.
  4. **Sharded-anakin A/B** (``--sharded-anakin-ab``): the fused
     act+train loop on a 1x1 mesh vs the same total lane count
     partitioned across a dp-wide (CPU-emulated) mesh — per-shard lane
     groups acting into local replay shards alongside the dp-sharded
     learner step — with per-arm medians and the env/learner scaling
     ratios in one artifact (``E2E_r12.json``).
  5. **Telemetry / learning / resources / tracing A/Bs**
     (``--telemetry-ab`` / ``--learning-ab`` / ``--resources-ab`` /
     ``--tracing-ab``): the same e2e system with the respective kill
     switch on vs off — the < 2% overhead budgets for the PR-4 stage
     telemetry, the PR-5 fused learning diagnostics (histograms,
     staleness, ΔQ cadence), the PR-7 machine-side pillar (memory
     sampling, RSS/CPU gauges, compile/retrace capture, the per-record
     alert pass), and the PR-19 cross-plane experience lineage (sampled
     ``Block.trace_ms`` stamps, ring mirrors, the env-step→gradient
     latency block).
  6. **Fleet A/B** (``--fleet-ab``): the lockstep multihost trainer (one
     controller over an emulated dp mesh) with ``telemetry.fleet_enabled``
     on vs off — the widened psum gauges, per-iteration lockstep timing,
     and the rank-0 FleetAggregator under the same < 2% budget
     (``E2E_r14.json``).
  7. **Quant A/B** (``--quant-ab``): the quantized inference plane
     (ISSUE 14) — thread-mode acting arm at ``network.inference_dtype``
     f32 vs int8 (ABBA medians; int8 cells carry the ``quant`` accuracy
     block), a serving-probe arm at both dtypes, and the analytic
     weight-bytes table with the >= 3x int8 streaming cut
     (``E2E_r16.json``).

Output: ONE JSON line (the driver artifact), also written to ``--out``.
Hermetic on any backend — the fake env and (for the e2e phase) a
CPU-feasible reduced training shape, recorded in the artifact.
"""

import json
import os
import sys
import time
from typing import List, Optional, Tuple

import numpy as np

# CPU-feasible e2e shape: the full system topology (process actors, shm
# ring, real learner) at a reduced frame/network/batch shape so BOTH sides
# sustain measurable rates on a small CPU host (this container has 2 cores;
# a batch x window learner step at the reference shape takes ~25 s there,
# starving the measurement). The artifact records the exact config; TPU
# runs can override back to the reference training shape.
E2E_CPU_OVERRIDES = {
    "env.frame_height": 42, "env.frame_width": 42,
    "network.hidden_dim": 128, "network.cnn_out_dim": 256,
    "network.conv_layers": ((16, 8, 4), (32, 4, 2)),
    "sequence.burn_in_steps": 8, "sequence.learning_steps": 5,
    "sequence.forward_steps": 3,
    "replay.capacity": 40_000, "replay.block_length": 80,
    "replay.batch_size": 8, "replay.learning_starts": 800,
    "runtime.save_interval": 0, "runtime.log_interval": 2.0,
}


def _bench_config(overrides: Optional[dict] = None):
    from r2d2_tpu.config import Config
    base = {"env.game_name": "Fake"}
    base.update(overrides or {})
    return Config().replace(**base)


def measure_actor_throughput(cfg, envs_per_actor: int, seconds: float = 5.0,
                             seed: int = 0) -> dict:
    """env-steps/s of ONE actor worker on the fake env: the scalar loop at
    envs_per_actor=1, the vectorized loop otherwise. Blocks are dropped at
    the sink — this isolates the generation side (policy inference + env
    stepping + LocalBuffer assembly), the part envs_per_actor batches."""
    import jax

    from r2d2_tpu.models.network import NetworkApply
    from r2d2_tpu.runtime.actor_loop import make_actor_env, make_actor_policy

    cfg = cfg.replace(**{"actor.envs_per_actor": envs_per_actor})
    net = NetworkApply(6, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    params = net.init(jax.random.PRNGKey(0))
    sink = lambda _block: None
    no_poll = lambda: None

    # the same construction path the orchestrator and actor processes use
    env = make_actor_env(cfg, 0, 0, seed)
    policy, run_loop = make_actor_policy(cfg, net, params, 0, seed,
                                         epsilon=cfg.actor.base_eps)
    run = lambda stop, cap: run_loop(cfg, env, policy, sink, no_poll, stop,
                                     max_env_steps=cap)

    # compile warm-up outside the timed window (the jitted step + the
    # bootstrap share one program; one step() compiles it)
    policy.step()

    deadline = [0.0]
    stop = lambda: time.time() >= deadline[0]
    t0 = time.time()
    deadline[0] = t0 + seconds
    steps = run(stop, None)
    elapsed = time.time() - t0
    return {"envs_per_actor": envs_per_actor, "env_steps": int(steps),
            "seconds": round(elapsed, 3),
            "env_steps_per_sec": round(steps / elapsed, 1)}


def run_actor_sweep(sweep: List[int], seconds: float = 5.0,
                    overrides: Optional[dict] = None) -> dict:
    """The scalar-vs-vectorized table; speedups are against the sweep's
    envs_per_actor=1 cell (the legacy loop's aggregate env-steps/s — what
    those same envs achieve when stepped one-at-a-time)."""
    cfg = _bench_config(overrides)
    cells = [measure_actor_throughput(cfg, k, seconds=seconds) for k in sweep]
    out = {"cells": cells}
    base = next((c for c in cells if c["envs_per_actor"] == 1), None)
    if base is not None:
        # only a measured k=1 cell may serve as the scalar baseline — a
        # sweep without one gets no speedup fields rather than a mislabel
        for c in cells:
            c["speedup_vs_scalar"] = round(
                c["env_steps_per_sec"] / base["env_steps_per_sec"], 2)
        out["scalar_env_steps_per_sec"] = base["env_steps_per_sec"]
    return out


def run_e2e(seconds: float = 60.0, envs_per_actor: int = 16,
            num_actors: int = 1, overrides: Optional[dict] = None,
            actor_mode: str = "process") -> dict:
    """Process-mode (default) vector actors feeding the REAL learner;
    both speeds measured from the same run's TrainMetrics records
    (steady-state mean: records after the first, when training has
    started). The serve A/B runs the same system in thread mode so the
    in-proc serving rung carries client-observed latencies."""
    from r2d2_tpu.runtime.orchestrator import train

    ov = dict(E2E_CPU_OVERRIDES)
    ov.update({"actor.num_actors": num_actors,
               "actor.envs_per_actor": envs_per_actor})
    ov.update(overrides or {})
    # bench runs must not litter the default save_dir with telemetry
    # streams (save_interval is 0 here, but spans/metrics still write);
    # a scratch dir we created is removed again after the run
    scratch = None
    if "runtime.save_dir" not in ov:
        import tempfile
        scratch = tempfile.mkdtemp(prefix="r2d2_e2e_")
        ov["runtime.save_dir"] = scratch
    cfg = _bench_config(ov)
    records = []
    t0 = time.time()
    try:
        stacks = train(cfg, max_seconds=seconds, actor_mode=actor_mode,
                       log_fn=records.append)
    finally:
        if scratch is not None:
            import shutil
            shutil.rmtree(scratch, ignore_errors=True)
    elapsed = time.time() - t0
    learner = stacks[0].learner
    batch = cfg.replay.batch_size
    # steady state: drop the first record (warm-up/fill dominates it) and
    # records where training had not started; if NONE qualify (run too
    # short to train) the steady-state speeds report 0 — the *_overall
    # fields still carry the whole-run rates, never mislabeled warm-up
    steady = [r for r in records[1:] if r.get("training_speed")]
    env_speed = (float(np.mean([r["buffer_speed"] for r in steady]))
                 if steady else 0.0)
    train_speed = (float(np.mean([r["training_speed"] for r in steady]))
                   if steady else 0.0)
    # ingestion observability (TrainMetrics ingest counters, ISSUE 2)
    blocks_total = learner.metrics.ingest_blocks_total
    bpd = [r["ingest_blocks_per_drain"] for r in records
           if r.get("ingest_blocks_per_drain")]
    lat = [r["ingest_drain_latency_ms"] for r in records
           if r.get("ingest_drain_latency_ms") is not None]
    # telemetry evidence (ISSUE 4): per-stage the newest summary seen in
    # any record (union, not last-record-only: the board flush cadence can
    # exceed this shape's short log interval, so actor stages land in
    # SOME intervals — at the production log_interval every record has
    # them)
    stages = {}
    for r in records:
        stages.update(r.get("stages") or {})
    stages = stages or None
    # learning-diagnostics evidence (ISSUE 5): newest non-null value per
    # field across the records (ΔQ fires on its own step cadence, so most
    # short log intervals carry None for it — field-wise merge keeps the
    # last real sample); histogram bucket dumps stripped (the artifact
    # wants the summary, not 3x64 counts)
    learning = None
    for r in records:
        lb = r.get("learning")
        if not lb:
            continue
        clean = {k: v for k, v in lb.items() if not k.endswith("_counts")}
        if learning is None:
            learning = clean
        else:
            learning.update(
                {k: v for k, v in clean.items() if v is not None})
    # sharded-anakin evidence (ISSUE 8): the newest per-shard block (dp,
    # lanes/shard, per-shard env steps, imbalance); absent on non-anakin
    # runs
    anakin = next((r["anakin"] for r in reversed(records)
                   if r.get("anakin")), None)
    # replay-diagnostics evidence (ISSUE 10): field-wise merge, newest
    # non-null value per sub-block (tree snapshots fire on their own
    # cadence; evictions only appear once the ring wraps), histogram
    # count dumps stripped like the learning block's
    replay_diag = None
    for r in records:
        rd = r.get("replay_diag")
        if not rd:
            continue
        clean = {k: ({kk: vv for kk, vv in v.items()
                      if not kk.endswith("_counts")}
                     if isinstance(v, dict) else v)
                 for k, v in rd.items()}
        if replay_diag is None:
            replay_diag = clean
        else:
            replay_diag.update(
                {k: v for k, v in clean.items() if v is not None})
    # serving evidence (ISSUE 13): field-wise merge of the serving
    # blocks, newest non-null per sub-field (the latency histogram and
    # batch stats reset per interval, so one quiet interval must not
    # blank the evidence); present only on inference="server" runs
    serving = None
    for r in records:
        sb = r.get("serving")
        if not sb:
            continue
        if serving is None:
            serving = dict(sb)
        else:
            serving.update({k: v for k, v in sb.items() if v is not None})
    # quantized-inference evidence (ISSUE 14): probe COUNTS accumulate
    # across the run (per-interval ints read 0, not None, in a
    # probe-free interval — last-wins would erase the run's evidence);
    # the quality gauges take the newest non-null value. None on every
    # inference_dtype="f32" run (the sibling serving/anakin convention:
    # the key is always present, null when the plane is off).
    quant = None
    for r in records:
        qb = r.get("quant")
        if not qb:
            continue
        if quant is None:
            quant = dict(qb)
            continue
        for k, v in qb.items():
            if k in ("probes", "lanes_probed"):
                quant[k] = (quant.get(k) or 0) + (v or 0)
            elif v is not None:
                quant[k] = v
    # elastic-fleet evidence (ISSUE 15): field-wise merge of the
    # replay_service blocks, newest non-null per sub-block (membership
    # joins/leaves are cumulative so last-wins is exact; spill interval
    # counters take the newest populated snapshot); None on every run
    # with no fleet plane configured (the key-absence contract)
    replay_service = None
    for r in records:
        fb = r.get("replay_service")
        if not fb:
            continue
        if replay_service is None:
            replay_service = dict(fb)
        else:
            replay_service.update(
                {k: v for k, v in fb.items() if v is not None})
    # experience-lineage evidence (ISSUE 19): sampled COUNTS accumulate
    # across records (each interval_block consumes its interval, so
    # last-wins would erase the run's tally); the latency histograms
    # take the newest non-null summary. None on every run with
    # tracing_enabled off (the key-absence contract).
    trace = None
    for r in records:
        tb = r.get("trace")
        if not tb:
            continue
        if trace is None:
            trace = dict(tb)
            continue
        for k, v in tb.items():
            if k == "sampled":
                trace[k] = (trace.get(k) or 0) + (v or 0)
            elif v is not None:
                trace[k] = v
    # policy-quality evidence (ISSUE 20): sub-block-wise merge, newest
    # non-null (the eval snapshot persists across intervals and every
    # sub-block carries its own cumulative totals, so last-wins is
    # exact; interval-consumed calibration/shadow extrema take the
    # newest populated interval). None on every run with
    # quality_enabled off (the key-absence contract).
    quality = None
    for r in records:
        qy = r.get("quality")
        if not qy:
            continue
        if quality is None:
            quality = dict(qy)
        else:
            quality.update({k: v for k, v in qy.items() if v is not None})
    # crash-recovery evidence (ISSUE 18): the newest recovery block —
    # its snapshot counters are cumulative, so last-wins is exact; None
    # on every run with the snapshot plane off (the key-absence
    # contract, like serving/quant/replay_service)
    recovery = next((r["recovery"] for r in reversed(records)
                     if r.get("recovery")), None)
    # system-health evidence (ISSUE 7): the newest resources block plus
    # the run's alert tally — proof the pillar actually flowed (or, with
    # the kill switch off, that the records carried neither key)
    resources = next((r["resources"] for r in reversed(records)
                      if r.get("resources")), None)
    alerts_fired = sum(len((r.get("alerts") or {}).get("fired") or [])
                       for r in records)
    alerts_present = any("alerts" in r for r in records)
    return {
        "seconds": round(elapsed, 1),
        "num_actors": num_actors,
        "envs_per_actor": envs_per_actor,
        "ingest_batch_blocks": learner._ingest_k,
        "total_env_steps": int(learner.env_steps),
        "total_train_steps": int(learner.training_steps),
        "env_steps_per_sec": round(env_speed, 1),
        "learner_steps_per_sec": round(train_speed, 2),
        "learner_seq_updates_per_sec": round(train_speed * batch, 1),
        "env_steps_per_sec_overall": round(learner.env_steps / elapsed, 1),
        "learner_steps_per_sec_overall": round(
            learner.training_steps / elapsed, 2),
        "blocks_ingested": int(blocks_total),
        "blocks_ingested_per_sec": round(blocks_total / elapsed, 2),
        "ingest_blocks_per_drain": (round(float(np.mean(bpd)), 2)
                                    if bpd else None),
        "ingest_drain_latency_ms": (round(float(np.mean(lat)), 3)
                                    if lat else None),
        "ingest_pause_time": round(
            sum(r.get("ingest_pause_time") or 0.0 for r in records), 3),
        "batch_size": batch,
        "records": len(records),
        "stages": stages,
        "learning": learning,
        "replay_diag": replay_diag,
        "anakin": anakin,
        "serving": serving,
        "quant": quant,
        "trace": trace,
        "quality": quality,
        "replay_service": replay_service,
        "recovery": recovery,
        "resources": resources,
        "alerts_present": alerts_present,
        "alerts_fired": alerts_fired,
        "config": {k: ov[k] for k in sorted(ov)},
    }


def run_ingest_ab(seconds: float, envs_per_actor: int, num_actors: int,
                  ingest_blocks: int, overrides: Optional[dict] = None
                  ) -> dict:
    """Ingestion A/B (ISSUE 2 acceptance): the SAME e2e system run twice on
    this host — batched+pipelined ingestion (replay.ingest_batch_blocks =
    ``ingest_blocks``) vs the legacy per-block path (= 1) — in one
    artifact. The claim under test: higher learner updates/s at unchanged
    env-steps/s when per-block dispatch leaves the learner's critical
    path."""
    out = {}
    for label, k in (("ingest_off", 1), ("ingest_on", ingest_blocks)):
        ov = dict(overrides or {})
        ov["replay.ingest_batch_blocks"] = k
        out[label] = run_e2e(seconds, envs_per_actor, num_actors,
                             overrides=ov)
    off, on = out["ingest_off"], out["ingest_on"]
    if off["learner_steps_per_sec"] > 0:
        out["learner_speedup"] = round(
            on["learner_steps_per_sec"] / off["learner_steps_per_sec"], 3)
    if off["env_steps_per_sec"] > 0:
        out["env_steps_ratio"] = round(
            on["env_steps_per_sec"] / off["env_steps_per_sec"], 3)
    return out


def run_telemetry_ab(seconds: float, envs_per_actor: int, num_actors: int,
                     overrides: Optional[dict] = None) -> dict:
    """Telemetry overhead A/B (ISSUE 4 acceptance): the SAME e2e system
    run twice — ``telemetry.enabled`` on vs off — in one artifact. The
    budget under test: full telemetry (per-stage histograms on every
    pipeline hot path, span rings, board publication) costs < 2%
    env-steps/s. The ON cell also carries the aggregated stage
    percentiles as evidence the instrumentation actually flowed."""
    out = {}
    for label, on in (("telemetry_off", False), ("telemetry_on", True)):
        ov = dict(overrides or {})
        ov["telemetry.enabled"] = on
        out[label] = run_e2e(seconds, envs_per_actor, num_actors,
                             overrides=ov)
    off, on_ = out["telemetry_off"], out["telemetry_on"]
    if off["env_steps_per_sec"] > 0:
        ratio = on_["env_steps_per_sec"] / off["env_steps_per_sec"]
        out["env_steps_ratio"] = round(ratio, 3)
        out["overhead_pct"] = round((1.0 - ratio) * 100.0, 2)
    if off["learner_steps_per_sec"] > 0:
        out["learner_steps_ratio"] = round(
            on_["learner_steps_per_sec"] / off["learner_steps_per_sec"], 3)
    out["stage_count_on"] = len(on_.get("stages") or {})
    return out


def run_learning_ab(seconds: float, envs_per_actor: int, num_actors: int,
                    overrides: Optional[dict] = None,
                    repeats: int = 2) -> dict:
    """Learning-diagnostics overhead A/B (ISSUE 5 acceptance): the SAME
    e2e system with ``telemetry.learning_enabled`` on vs off, in one
    artifact. Budget under test: fused histograms + staleness stamps +
    the interval-gated ΔQ unrolls cost < 2% on BOTH env-steps/s and
    learner updates/s. The ON cell carries the aggregated ``learning``
    block (ΔQ stored/zero/recomputed, sample ages, grad norms) as
    evidence the diagnostics actually flowed end-to-end.

    Cells run INTERLEAVED off/on ``repeats`` times and the headline
    ratios come from per-arm medians: on a small shared host the actor
    side swings ±10% run-to-run (2-core scheduling noise dwarfs the
    effect under test — the telemetry-AB round hit the same wall), and a
    single pair routinely reports whichever way the wind blew. Every
    cell's speeds stay in the artifact."""
    cells = {"learning_off": [], "learning_on": []}
    for _ in range(max(repeats, 1)):
        for label, on in (("learning_off", False), ("learning_on", True)):
            ov = dict(overrides or {})
            ov["telemetry.learning_enabled"] = on
            # dQ must FIRE inside the window for the evidence fields, but
            # its cadence is the measurement: one reference unroll costs
            # ~2 train steps (measured on the CPU e2e shape), so
            # interval=100 amortizes to ~1% of learner time — the
            # production default (200) halves that again. Forcing a tight
            # cadence here would measure a config nobody runs.
            ov.setdefault("telemetry.learning_interval", 100)
            cells[label].append(run_e2e(seconds, envs_per_actor,
                                        num_actors, overrides=ov))

    def med(label, key):
        return float(np.median([c[key] for c in cells[label]]))

    out = {"learning_off": cells["learning_off"][-1],
           "learning_on": cells["learning_on"][-1],
           "repeats": max(repeats, 1),
           "env_steps_per_sec_cells": {
               k: [c["env_steps_per_sec"] for c in v]
               for k, v in cells.items()},
           "learner_steps_per_sec_cells": {
               k: [c["learner_steps_per_sec"] for c in v]
               for k, v in cells.items()}}
    if med("learning_off", "env_steps_per_sec") > 0:
        ratio = (med("learning_on", "env_steps_per_sec")
                 / med("learning_off", "env_steps_per_sec"))
        out["env_steps_ratio"] = round(ratio, 3)
        out["overhead_pct"] = round((1.0 - ratio) * 100.0, 2)
    if med("learning_off", "learner_steps_per_sec") > 0:
        out["learner_steps_ratio"] = round(
            med("learning_on", "learner_steps_per_sec")
            / med("learning_off", "learner_steps_per_sec"), 3)
    # evidence: newest ON cell carrying each field
    lb = {}
    for c in cells["learning_on"]:
        lb.update({k: v for k, v in (c.get("learning") or {}).items()
                   if v is not None})
    out["learning_block_on"] = bool(lb)
    out["delta_q_on"] = lb.get("delta_q")
    out["sample_age_on"] = lb.get("sample_age")
    out["learning_block_off"] = any(
        c.get("learning") for c in cells["learning_off"])
    return out


def run_resources_ab(seconds: float, envs_per_actor: int, num_actors: int,
                     overrides: Optional[dict] = None,
                     repeats: int = 2) -> dict:
    """Resource/compile/alerts overhead A/B (ISSUE 7 acceptance): the
    SAME e2e system with ``telemetry.resources_enabled`` on vs off, in
    one artifact. Budget under test: the machine-side pillar — periodic
    ``memory_stats`` sampling + buffer attribution, per-actor-slot
    RSS/CPU gauges through the shm board, the compile/retrace log
    listener, and the per-record alert-rule pass — costs < 2% on BOTH
    env-steps/s and learner updates/s (the PR4 budget). Cells run
    INTERLEAVED off/on ``repeats`` times with per-arm medians, exactly
    like the learning A/B (single cells swing ±10% on the 2-core host).
    The ON cells carry the ``resources`` block + the alert tally as
    evidence the pillar actually flowed; the OFF cells prove the records
    carried neither key (the kill-switch schema contract)."""
    cells = {"resources_off": [], "resources_on": []}
    for _ in range(max(repeats, 1)):
        for label, on in (("resources_off", False), ("resources_on", True)):
            ov = dict(overrides or {})
            ov["telemetry.resources_enabled"] = on
            # sample every interval at this short log cadence — the
            # PRODUCTION default (10 s) samples less often, so benching
            # the tighter cadence bounds the real overhead from above
            ov.setdefault("telemetry.resources_interval_s", 2.0)
            cells[label].append(run_e2e(seconds, envs_per_actor,
                                        num_actors, overrides=ov))

    def med(label, key):
        return float(np.median([c[key] for c in cells[label]]))

    out = {"resources_off": cells["resources_off"][-1],
           "resources_on": cells["resources_on"][-1],
           "repeats": max(repeats, 1),
           "env_steps_per_sec_cells": {
               k: [c["env_steps_per_sec"] for c in v]
               for k, v in cells.items()},
           "learner_steps_per_sec_cells": {
               k: [c["learner_steps_per_sec"] for c in v]
               for k, v in cells.items()}}
    if med("resources_off", "env_steps_per_sec") > 0:
        ratio = (med("resources_on", "env_steps_per_sec")
                 / med("resources_off", "env_steps_per_sec"))
        out["env_steps_ratio"] = round(ratio, 3)
        out["overhead_pct"] = round((1.0 - ratio) * 100.0, 2)
    if med("resources_off", "learner_steps_per_sec") > 0:
        out["learner_steps_ratio"] = round(
            med("resources_on", "learner_steps_per_sec")
            / med("resources_off", "learner_steps_per_sec"), 3)
    on_cells = cells["resources_on"]
    out["resources_block_on"] = any(c.get("resources") for c in on_cells)
    out["alerts_block_on"] = any(c.get("alerts_present") for c in on_cells)
    out["alerts_fired_on"] = sum(c.get("alerts_fired") or 0
                                 for c in on_cells)
    rb = next((c["resources"] for c in reversed(on_cells)
               if c.get("resources")), None)
    out["compile_block_on"] = bool(rb and rb.get("compile"))
    out["resources_block_off"] = any(
        c.get("resources") for c in cells["resources_off"])
    out["alerts_block_off"] = any(
        c.get("alerts_present") for c in cells["resources_off"])
    return out


def run_recovery_ab(seconds: float, envs_per_actor: int, num_actors: int,
                    overrides: Optional[dict] = None,
                    repeats: int = 2,
                    snapshot_interval: int = 200) -> dict:
    """Crash-recovery plane overhead A/B (ISSUE 18 acceptance): the SAME
    e2e system with ``runtime.snapshot_interval`` on vs off, in one
    artifact. Budget under test: the durable replay snapshot path —
    per-interval device→host ring capture, the async SnapshotWriter's
    npz serialization + atomic tmp/rename commit, and the recovery
    telemetry block — costs < 2% on BOTH env-steps/s and learner
    updates/s (the capture is the only on-path piece; the write rides a
    background thread). Cells run INTERLEAVED off/on ``repeats`` times
    with per-arm medians, like the resources A/B. The ON cells carry
    the ``recovery`` block (snapshot count/bytes/write_s) as evidence
    snapshots actually flowed; the OFF cells prove the records carried
    no ``recovery`` key at all (the kill-switch schema contract)."""
    cells = {"recovery_off": [], "recovery_on": []}
    for _ in range(max(repeats, 1)):
        for label, interval in (("recovery_off", 0),
                                ("recovery_on", snapshot_interval)):
            ov = dict(overrides or {})
            ov["runtime.snapshot_interval"] = interval
            cells[label].append(run_e2e(seconds, envs_per_actor,
                                        num_actors, overrides=ov))

    def med(label, key):
        return float(np.median([c[key] for c in cells[label]]))

    out = {"recovery_off": cells["recovery_off"][-1],
           "recovery_on": cells["recovery_on"][-1],
           "repeats": max(repeats, 1),
           "snapshot_interval": snapshot_interval,
           "env_steps_per_sec_cells": {
               k: [c["env_steps_per_sec"] for c in v]
               for k, v in cells.items()},
           "learner_steps_per_sec_cells": {
               k: [c["learner_steps_per_sec"] for c in v]
               for k, v in cells.items()}}
    if med("recovery_off", "env_steps_per_sec") > 0:
        ratio = (med("recovery_on", "env_steps_per_sec")
                 / med("recovery_off", "env_steps_per_sec"))
        out["env_steps_ratio"] = round(ratio, 3)
        out["overhead_pct"] = round((1.0 - ratio) * 100.0, 2)
    if med("recovery_off", "learner_steps_per_sec") > 0:
        out["learner_steps_ratio"] = round(
            med("recovery_on", "learner_steps_per_sec")
            / med("recovery_off", "learner_steps_per_sec"), 3)
    on_cells = cells["recovery_on"]
    out["recovery_block_on"] = any(c.get("recovery") for c in on_cells)
    rb = next((c["recovery"] for c in reversed(on_cells)
               if c.get("recovery")), None)
    if rb:
        out["snapshots_written"] = (rb.get("snapshot") or {}).get("count")
        out["snapshot_bytes"] = (rb.get("snapshot") or {}).get("bytes")
        out["snapshot_write_s"] = (rb.get("snapshot") or {}).get("write_s")
    out["recovery_block_off"] = any(
        c.get("recovery") for c in cells["recovery_off"])
    return out


def run_tracing_ab(seconds: float, envs_per_actor: int, num_actors: int,
                   overrides: Optional[dict] = None,
                   repeats: int = 2) -> dict:
    """Cross-plane tracing overhead A/B (ISSUE 19 acceptance): the SAME
    e2e system with ``telemetry.tracing_enabled`` on vs off, in one
    artifact. Budget under test: the lineage path — the per-emission
    sampled stamp on ``Block.trace_ms``, the strip-before-device-commit
    + ring-mirror bookkeeping inside the ingest path, the sample-time
    slot lookup, and the per-record ``trace`` block assembly — costs
    <= 2%% on BOTH env-steps/s and learner updates/s. Cells run
    ABBA-interleaved ``repeats`` times with per-arm medians (the
    serve/fleet-AB noise treatment; single cells swing ±10%% on the
    2-core host). The ON cells carry the ``trace`` block (sampled rows,
    the env-step->gradient e2e histogram, per-hop breakdown) as
    end-to-end evidence; the OFF cells prove the records carried no
    ``trace`` key at all (the kill-switch schema contract)."""
    cells = {"tracing_off": [], "tracing_on": []}
    for rep in range(max(repeats, 1)):
        order = (("tracing_off", False), ("tracing_on", True))
        if rep % 2:
            order = order[::-1]    # ABBA: cancel monotonic host drift
        for label, on in order:
            ov = dict(overrides or {})
            ov["telemetry.tracing_enabled"] = on
            # trace a denser fraction than the production default so the
            # short window accumulates real histograms — stamping MORE
            # blocks bounds the per-emission overhead from above
            ov.setdefault("telemetry.trace_sample_every", 4)
            # lineage lives on the replay-service path (the ring-mirror
            # bookkeeping under test); BOTH arms run it so the A/B
            # isolates tracing, not the service plane itself
            ov.setdefault("fleet.replay_shards", 1)
            cells[label].append(run_e2e(seconds, envs_per_actor,
                                        num_actors, overrides=ov))

    def med(label, key):
        return float(np.median([c[key] for c in cells[label]]))

    out = {"tracing_off": cells["tracing_off"][-1],
           "tracing_on": cells["tracing_on"][-1],
           "repeats": max(repeats, 1),
           "env_steps_per_sec_cells": {
               k: [c["env_steps_per_sec"] for c in v]
               for k, v in cells.items()},
           "learner_steps_per_sec_cells": {
               k: [c["learner_steps_per_sec"] for c in v]
               for k, v in cells.items()}}
    if med("tracing_off", "env_steps_per_sec") > 0:
        ratio = (med("tracing_on", "env_steps_per_sec")
                 / med("tracing_off", "env_steps_per_sec"))
        out["env_steps_ratio"] = round(ratio, 3)
        out["overhead_pct"] = round((1.0 - ratio) * 100.0, 2)
    if med("tracing_off", "learner_steps_per_sec") > 0:
        out["learner_steps_ratio"] = round(
            med("tracing_on", "learner_steps_per_sec")
            / med("tracing_off", "learner_steps_per_sec"), 3)
    # evidence: merge the ON cells' trace blocks (counts sum, hop
    # summaries newest-non-null — the run_e2e merge semantics again)
    tb = {}
    for c in cells["tracing_on"]:
        for k, v in (c.get("trace") or {}).items():
            if k == "sampled":
                tb[k] = (tb.get(k) or 0) + (v or 0)
            elif v is not None:
                tb[k] = v
    out["trace_block_on"] = bool(tb)
    out["traced_rows_on"] = tb.get("sampled")
    e2e = tb.get("e2e_experience_latency") or {}
    out["e2e_latency_p50_ms"] = e2e.get("p50_ms")
    out["e2e_latency_p95_ms"] = e2e.get("p95_ms")
    out["hops_on"] = sorted((tb.get("hops") or {}).keys())
    out["trace_block_off"] = any(
        c.get("trace") for c in cells["tracing_off"])
    return out


def run_promotion_ab(seconds: float, envs_per_actor: int, num_actors: int,
                     overrides: Optional[dict] = None,
                     repeats: int = 2) -> dict:
    """Policy-quality overhead A/B + promotion-drill evidence (ISSUE 20
    acceptance): the SAME e2e system with ``telemetry.quality_enabled``
    on vs off, in one artifact. Budget under test: the quality plane's
    in-band costs — the per-block calibration tap inside
    ``LocalBuffer.finish`` (run at sample_every=1, bounding the
    production cadence from above), the QualityStats aggregation, and
    the per-record ``quality`` block + ``quality_player{p}.jsonl``
    ledger row assembly — cost <= 2%% on BOTH env-steps/s and learner
    updates/s. Cells run ABBA-interleaved ``repeats`` times with
    per-arm medians (the tracing-AB noise treatment) in THREAD mode so
    the calibration tap actually rides the acting hot path. The ON
    cells carry the ``quality`` block as end-to-end evidence; the OFF
    cells prove the records carried no ``quality`` key at all (the
    kill-switch schema contract).

    A final evidence cell runs the full gated-canary promotion drill
    (tools/chaos.py ``--promotion``): corrupted candidate refused with
    ``canary_divergence`` fired exactly once, healthy candidate
    promoted fleet-wide via ONE root publish, bit-identical rollback."""
    cells = {"quality_off": [], "quality_on": []}
    for rep in range(max(repeats, 1)):
        order = (("quality_off", False), ("quality_on", True))
        if rep % 2:
            order = order[::-1]    # ABBA: cancel monotonic host drift
        for label, on in order:
            ov = dict(overrides or {})
            ov["telemetry.quality_enabled"] = on
            # every finished block feeds the calibration join — denser
            # than any production cadence, so the measured overhead
            # bounds the per-emission cost from above
            ov.setdefault("telemetry.quality_calib_sample_every", 1)
            cells[label].append(run_e2e(seconds, envs_per_actor,
                                        num_actors, overrides=ov,
                                        actor_mode="thread"))

    def med(label, key):
        return float(np.median([c[key] for c in cells[label]]))

    out = {"quality_off": cells["quality_off"][-1],
           "quality_on": cells["quality_on"][-1],
           "repeats": max(repeats, 1),
           "env_steps_per_sec_cells": {
               k: [c["env_steps_per_sec"] for c in v]
               for k, v in cells.items()},
           "learner_steps_per_sec_cells": {
               k: [c["learner_steps_per_sec"] for c in v]
               for k, v in cells.items()}}
    if med("quality_off", "env_steps_per_sec") > 0:
        ratio = (med("quality_on", "env_steps_per_sec")
                 / med("quality_off", "env_steps_per_sec"))
        out["env_steps_ratio"] = round(ratio, 3)
        out["overhead_pct"] = round((1.0 - ratio) * 100.0, 2)
    if med("quality_off", "learner_steps_per_sec") > 0:
        out["learner_steps_ratio"] = round(
            med("quality_on", "learner_steps_per_sec")
            / med("quality_off", "learner_steps_per_sec"), 3)
    # evidence: merge the ON cells' quality blocks (sub-blocks carry
    # their own cumulative totals, newest-non-null — the run_e2e merge
    # semantics again)
    qb = {}
    for c in cells["quality_on"]:
        for k, v in (c.get("quality") or {}).items():
            if v is not None:
                qb[k] = v
    out["quality_block_on"] = bool(qb)
    out["calibration_samples_on"] = (
        (qb.get("calibration") or {}).get("samples_total"))
    out["promotion_state_on"] = (qb.get("promotion") or {}).get("state")
    out["quality_block_off"] = any(
        c.get("quality") for c in cells["quality_off"])
    # the promotion-drill evidence cell: real servers, real mirrors,
    # real fan-out — the acceptance's refuse/promote/rollback proof
    from r2d2_tpu.tools.chaos import run_promotion_drill
    drill = run_promotion_drill(max(seconds, 60.0))
    out["promotion_drill"] = {
        "passed": all(drill["verdict"].values()),
        "verdict": drill["verdict"],
        "corrupt_divergence": drill.get("corrupt_divergence"),
        "healthy_divergence": drill.get("healthy_divergence"),
        "promoted_stamp": drill.get("promoted_stamp"),
        "rolled_back_to_stamp": drill.get("rolled_back_to_stamp"),
        "alerts_fired": drill.get("alerts_fired"),
    }
    return out


def run_replay_diag_ab(seconds: float, envs_per_actor: int, num_actors: int,
                       overrides: Optional[dict] = None,
                       repeats: int = 2, sharded_dp: int = 2) -> dict:
    """Replay-diagnostics overhead A/B (ISSUE 10 acceptance): the SAME
    e2e host-actor system with ``telemetry.replay_diag_enabled`` on vs
    off, in one artifact. Budget under test: the fused pillar — the
    per-step sample-count scatter + lane bincount, the interval-gated
    sum-tree snapshot, and eviction accounting inside replay_add_many —
    costs < 2% on BOTH env-steps/s and learner updates/s. Cells run
    INTERLEAVED off/on ``repeats`` times with per-arm medians (the
    learning/resources-AB noise treatment; single cells swing ±10% on
    the 2-core host).

    A final evidence cell runs the SHARDED (emulated dp=``sharded_dp``)
    anakin loop with the pillar on — the acceptance's second path — and
    records its ``replay_diag`` block with per-shard + merged sum-tree
    views. Requires >= sharded_dp visible devices (main forces the CPU
    host-device count when it owns the process)."""
    cells = {"replay_diag_off": [], "replay_diag_on": []}
    for _ in range(max(repeats, 1)):
        for label, on in (("replay_diag_off", False),
                          ("replay_diag_on", True)):
            ov = dict(overrides or {})
            ov["telemetry.replay_diag_enabled"] = on
            # the snapshot must FIRE inside the short window for the
            # evidence fields; interval=20 is ~4x the production cadence
            # relative to step rate on this shape, bounding overhead
            # from above
            ov.setdefault("telemetry.replay_diag_interval", 20)
            cells[label].append(run_e2e(seconds, envs_per_actor,
                                        num_actors, overrides=ov))

    def med(label, key):
        return float(np.median([c[key] for c in cells[label]]))

    out = {"replay_diag_off": cells["replay_diag_off"][-1],
           "replay_diag_on": cells["replay_diag_on"][-1],
           "repeats": max(repeats, 1),
           "env_steps_per_sec_cells": {
               k: [c["env_steps_per_sec"] for c in v]
               for k, v in cells.items()},
           "learner_steps_per_sec_cells": {
               k: [c["learner_steps_per_sec"] for c in v]
               for k, v in cells.items()}}
    if med("replay_diag_off", "env_steps_per_sec") > 0:
        ratio = (med("replay_diag_on", "env_steps_per_sec")
                 / med("replay_diag_off", "env_steps_per_sec"))
        out["env_steps_ratio"] = round(ratio, 3)
        out["overhead_pct"] = round((1.0 - ratio) * 100.0, 2)
    if med("replay_diag_off", "learner_steps_per_sec") > 0:
        out["learner_steps_ratio"] = round(
            med("replay_diag_on", "learner_steps_per_sec")
            / med("replay_diag_off", "learner_steps_per_sec"), 3)
    # evidence: newest ON cell carrying each sub-block (host-actor path)
    rd = {}
    for c in cells["replay_diag_on"]:
        rd.update({k: v for k, v in (c.get("replay_diag") or {}).items()
                   if v is not None})
    out["replay_diag_block_on"] = bool(rd)
    out["tree_on"] = rd.get("tree")
    out["evictions_on"] = rd.get("evictions")
    out["lanes_on"] = rd.get("lanes")
    out["replay_diag_block_off"] = any(
        c.get("replay_diag") for c in cells["replay_diag_off"])

    # the sharded-anakin evidence cell: per-shard + merged tree views on
    # the emulated dp mesh (the acceptance's second path)
    import jax
    if len(jax.devices()) >= sharded_dp:
        ov = dict(ANAKIN_AB_OVERRIDES)
        ov.update(overrides or {})
        ov.update({"actor.on_device": True, "actor.anakin_lanes": 64,
                   "mesh.dp": sharded_dp,
                   "telemetry.replay_diag_enabled": True,
                   "telemetry.replay_diag_interval": 5})
        cell = run_e2e(seconds, overrides=ov)
        out["sharded_anakin_on"] = cell
        srd = cell.get("replay_diag") or {}
        out["sharded_tree_on"] = srd.get("tree")
        out["sharded_shards_on"] = srd.get("shards")
    return out


def run_serve_ab(seconds: float, lanes: int = 16,
                 overrides: Optional[dict] = None,
                 repeats: int = 2, sweep: Tuple[int, ...] = (1, 4, 16)
                 ) -> dict:
    """Serving overhead + batching-under-load evidence (ISSUE 13
    acceptance): the SAME thread-mode e2e system — one vector actor
    worker whose lanes each hold a serve client — with
    ``actor.inference`` local vs server at equal lanes, ABBA-interleaved
    ``repeats`` times with per-arm medians (the fleet-AB noise
    treatment), PLUS a client-count sweep (server mode at 1/4/16 lanes)
    recording the batch-fill climb with load.

    The claims under test on this CPU container: server-mode aggregate
    env-steps/s stays within 0.8x of local at 16 clients (the mechanism
    is not pathological — the WIN is placement on real accelerators,
    where the batched forward leaves the actor host entirely), mean
    batch fill > 1 from 4 clients up, and P99 request latency bounded by
    the deadline + one forward. Thread mode keeps the in-proc rung under
    test (client-observed latency in the serving block); the process
    rungs (shm/socket) are round-trip-tested in tests/test_serve.py."""
    base = dict(overrides or {})
    cells = {"local": [], "server": []}
    for rep in range(max(repeats, 1)):
        order = (("local", "local"), ("server", "server"))
        if rep % 2:
            order = order[::-1]    # ABBA: cancel monotonic host drift
        for label, mode in order:
            ov = dict(base)
            ov["actor.inference"] = mode
            cells[label].append(run_e2e(
                seconds, envs_per_actor=lanes, num_actors=1,
                overrides=ov, actor_mode="thread"))

    def med(label, key):
        return float(np.median([c[key] for c in cells[label]]))

    out = {"local": cells["local"][-1], "server": cells["server"][-1],
           "lanes": lanes, "repeats": max(repeats, 1),
           "env_steps_per_sec_cells": {
               k: [c["env_steps_per_sec"] for c in v]
               for k, v in cells.items()},
           "learner_steps_per_sec_cells": {
               k: [c["learner_steps_per_sec"] for c in v]
               for k, v in cells.items()}}
    if med("local", "env_steps_per_sec") > 0:
        out["env_steps_ratio_serve"] = round(
            med("server", "env_steps_per_sec")
            / med("local", "env_steps_per_sec"), 3)
    if med("local", "learner_steps_per_sec") > 0:
        out["learner_steps_ratio_serve"] = round(
            med("server", "learner_steps_per_sec")
            / med("local", "learner_steps_per_sec"), 3)
    sb = next((c["serving"] for c in reversed(cells["server"])
               if c.get("serving")), None)
    out["serving_block_on"] = bool(sb)
    if sb:
        out["serve_latency_p99_ms"] = (sb.get("latency") or {}).get(
            "p99_ms")
        out["serve_fill_mean"] = (sb.get("batch") or {}).get("fill_mean")
    out["serving_block_local"] = any(c.get("serving")
                                     for c in cells["local"])

    # client-count sweep: batch fill climbing with load is the
    # micro-batcher's central claim — each lane is one blocking client,
    # so fill tracks the number of concurrently-pending requests. The
    # probe isolates the SERVING plane (no colocated learner): on this
    # 2-core host the integrated arms' tail latency is GIL/scheduler
    # contention with the training loop, which would mis-measure the
    # batcher itself; the SLO leg (p99 <= deadline + one forward) is
    # checked per cell against the same run's forward percentiles.
    out["client_sweep"] = [
        serve_latency_probe(min(seconds, 15.0), n, overrides=base)
        for n in sweep]
    fills = [c["fill_mean"] for c in out["client_sweep"]
             if c["fill_mean"] is not None]
    if fills:
        out["serve_fill_mean_sweep_max"] = max(fills)
    out["serve_slo_ok_sweep"] = all(
        c.get("slo_ok") for c in out["client_sweep"])
    return out


def quant_weight_bytes_table(overrides: Optional[dict] = None) -> dict:
    """Analytic weight-streaming table (ISSUE 14 acceptance): bytes of
    the acting forward's weight tree per inference dtype at the
    REFERENCE network shape (hidden 512 / cnn 1024 / Nature convs —
    what the TPU projection is about), plus this bench's reduced shape
    for context. Pure eval_shape math, no compile; the int8 ratio is
    the >= 3x cut the costmodel gate also snapshots exactly."""
    import dataclasses

    import jax

    from r2d2_tpu.config import Config, NetworkConfig
    from r2d2_tpu.models.network import (NetworkApply, param_tree_bytes,
                                         quantize_params)

    def row(ncfg, stack, h, w):
        net = NetworkApply(6, ncfg, stack, h, w)
        params = jax.eval_shape(net.init, jax.random.PRNGKey(0))
        out = {}
        for mode in ("f32", "bf16", "int8"):
            tree = (params if mode == "f32" else jax.eval_shape(
                lambda p, _m=mode: quantize_params(p, _m), params))
            out[f"weight_bytes_{mode}"] = param_tree_bytes(tree)
        for mode in ("bf16", "int8"):
            out[f"weight_bytes_ratio_{mode}"] = round(
                out["weight_bytes_f32"] / out[f"weight_bytes_{mode}"], 3)
        return out

    ref = Config()
    bench = _bench_config(dict(E2E_CPU_OVERRIDES, **(overrides or {})))
    return {
        "reference_shape": row(
            dataclasses.replace(NetworkConfig(), space_to_depth="off"),
            ref.env.frame_stack, ref.env.frame_height, ref.env.frame_width),
        "bench_shape": row(
            dataclasses.replace(bench.network, space_to_depth="off"),
            bench.env.frame_stack, bench.env.frame_height,
            bench.env.frame_width),
    }


def run_quant_ab(seconds: float, lanes: int = 16,
                 overrides: Optional[dict] = None,
                 repeats: int = 2) -> dict:
    """Quantized-inference A/B (ISSUE 14 acceptance), three arms in one
    artifact:

      * **acting arm** — the SAME thread-mode e2e system (one vector
        actor worker + the real learner) at ``network.inference_dtype``
        f32 vs int8, ABBA-interleaved ``repeats`` times with per-arm
        medians (the serve/fleet-AB noise treatment); the int8 cells
        carry the ``quant`` block (probes, agreement, |ΔQ|) as
        end-to-end evidence and f32 cells prove the records carry no
        ``quant`` key (the kill-switch schema contract);
      * **serving-probe arm** — the pure serving-plane latency probe
        (no colocated learner) at f32 vs int8: requests/s, batch fill,
        forward percentiles, the SLO leg;
      * **weight-bytes table** — the analytic streaming cut per dtype
        at the reference shape (the >= 3x int8 acceptance line, also
        exact-match-gated through the costmodel table).

    CPU-gate framing (PERF.md round 17): the acting ratio measures the
    weight-streaming mechanism one memory tier down — the bench-shape
    f32 tree spills this host's per-core cache while the int8 twin
    stays resident (measured 1.19x) — and the weight-bytes table is
    what projects to TPU, where the acting forward is
    HBM-streaming-bound (the costmodel bytes tables)."""
    base = dict(overrides or {})
    base.setdefault("telemetry.quant_probe_interval", 64)
    cells = {"acting_f32": [], "acting_int8": []}
    for rep in range(max(repeats, 1)):
        order = (("acting_f32", "f32"), ("acting_int8", "int8"))
        if rep % 2:
            order = order[::-1]    # ABBA: cancel monotonic host drift
        for label, mode in order:
            ov = dict(base)
            ov["network.inference_dtype"] = mode
            cells[label].append(run_e2e(
                seconds, envs_per_actor=lanes, num_actors=1,
                overrides=ov, actor_mode="thread"))

    def med(label, key):
        return float(np.median([c[key] for c in cells[label]]))

    out = {"acting_f32": cells["acting_f32"][-1],
           "acting_int8": cells["acting_int8"][-1],
           "lanes": lanes, "repeats": max(repeats, 1),
           "env_steps_per_sec_cells": {
               k: [c["env_steps_per_sec"] for c in v]
               for k, v in cells.items()},
           "learner_steps_per_sec_cells": {
               k: [c["learner_steps_per_sec"] for c in v]
               for k, v in cells.items()}}
    if med("acting_f32", "env_steps_per_sec") > 0:
        out["env_steps_ratio_quant"] = round(
            med("acting_int8", "env_steps_per_sec")
            / med("acting_f32", "env_steps_per_sec"), 3)
    if med("acting_f32", "learner_steps_per_sec") > 0:
        out["learner_steps_ratio_quant"] = round(
            med("acting_int8", "learner_steps_per_sec")
            / med("acting_f32", "learner_steps_per_sec"), 3)
    qb = {}
    for c in cells["acting_int8"]:
        qb.update({k: v for k, v in (c.get("quant") or {}).items()
                   if v is not None})
    out["quant_block_on"] = bool(qb)
    out["quant_agree_frac"] = qb.get("agree_frac")
    out["quant_dq_max"] = qb.get("dq_max")
    out["quant_probes"] = qb.get("probes")
    out["quant_block_f32"] = any(c.get("quant")
                                 for c in cells["acting_f32"])

    # serving-probe arm: the micro-batcher itself at each dtype — the
    # serving plane is the second consumer the ISSUE names, and the
    # probe isolates it from the training loop's core contention
    out["serve_probe"] = {}
    for mode in ("f32", "int8"):
        ov = dict(base)
        ov["network.inference_dtype"] = mode
        out["serve_probe"][mode] = serve_latency_probe(
            min(seconds, 15.0), lanes, overrides=ov)
    f32_rps = out["serve_probe"]["f32"].get("requests_per_sec") or 0
    if f32_rps > 0:
        out["serve_requests_ratio_quant"] = round(
            (out["serve_probe"]["int8"].get("requests_per_sec") or 0)
            / f32_rps, 3)
    out["serve_slo_ok_quant"] = bool(
        out["serve_probe"]["int8"].get("slo_ok"))

    out["weight_bytes"] = quant_weight_bytes_table(overrides)
    return out


def run_elastic_ab(seconds: float, overrides: Optional[dict] = None,
                   repeats: int = 2, num_actors: int = 4,
                   lanes_per_actor: int = 4) -> dict:
    """Elastic-fleet A/B (ISSUE 15 acceptance), two arm pairs in one
    artifact:

      * **churn arm** — the SAME thread-mode e2e system (num_actors
        vector workers + the real learner) fixed vs CHURNED at equal
        lanes: the churned cells run ``fleet.elastic`` with a
        grammar-injected ``leave@block`` on 25%% of the fleet and a
        ``join@t`` re-adoption mid-run (the supervisor admits the
        joiner; the slot's lane range/ε slice are adopted). ABBA-
        interleaved ``repeats`` times with per-arm medians; churned
        cells carry the ``replay_service`` membership block (joins/
        leaves) as end-to-end evidence. The claim: churn costs bounded
        throughput (the departed slot's share for the gap), and the
        learner NEVER stalls — training_speed stays nonzero in every
        churned record after warm-up.
      * **spill arm** — the service-routed learner
        (``fleet.replay_shards=2``) with the host-RAM spill tier off vs
        on (spill sized to 1x the device rings → 2x total capacity):
        learner updates/s ratio ON/OFF bounds the spill tier's cost on
        the training path, and the ON cell's spill occupancy/hit-rate
        prove pages actually demote and re-promote."""
    base = dict(overrides or {})
    lanes = num_actors * lanes_per_actor
    n_leave = max(1, int(num_actors * 0.25))
    join_at = max(seconds * 0.55, 10.0)
    spec_parts = []
    for s in range(n_leave):
        spec_parts.append(f"{s}:leave@block={30 + 5 * s}")
        spec_parts.append(f"{s}:join@t={join_at + 2.0 * s:.1f}")
    churn_ov = {
        "fleet.elastic": True,
        "actor.fault_spec": ";".join(spec_parts),
        "runtime.supervise_interval_s": 1.0,
    }
    cells = {"fixed": [], "churned": []}
    for rep in range(max(repeats, 1)):
        order = (("fixed", {}), ("churned", churn_ov))
        if rep % 2:
            order = order[::-1]    # ABBA: cancel monotonic host drift
        for label, extra in order:
            ov = dict(base)
            ov.update(extra)
            cells[label].append(run_e2e(
                seconds, envs_per_actor=lanes_per_actor,
                num_actors=num_actors, overrides=ov, actor_mode="thread"))

    def med(label, key):
        return float(np.median([c[key] for c in cells[label]]))

    out = {"fixed": cells["fixed"][-1], "churned": cells["churned"][-1],
           "lanes": lanes, "repeats": max(repeats, 1),
           "left_and_rejoined": n_leave,
           "env_steps_per_sec_cells": {
               k: [c["env_steps_per_sec"] for c in v]
               for k, v in cells.items()},
           "learner_steps_per_sec_cells": {
               k: [c["learner_steps_per_sec"] for c in v]
               for k, v in cells.items()}}
    if med("fixed", "env_steps_per_sec") > 0:
        out["env_steps_ratio_churn"] = round(
            med("churned", "env_steps_per_sec")
            / med("fixed", "env_steps_per_sec"), 3)
    if med("fixed", "learner_steps_per_sec") > 0:
        out["learner_steps_ratio_churn"] = round(
            med("churned", "learner_steps_per_sec")
            / med("fixed", "learner_steps_per_sec"), 3)
    mb = {}
    for c in cells["churned"]:
        mb.update(((c.get("replay_service") or {}).get("membership")
                   or {}))
    out["membership_block_on"] = bool(mb)
    out["churn_joins"] = mb.get("joins")
    out["churn_leaves"] = mb.get("leaves")
    out["membership_block_fixed"] = any(c.get("replay_service")
                                        for c in cells["fixed"])

    # spill arm: the service-routed learner with the spill tier off/on.
    # Device rings shrink so the ring cycles within the bench window
    # (demotions need overwrites); spill ON sizes the tier to the whole
    # device budget — 2x effective capacity, the acceptance geometry.
    svc_base = dict(base)
    svc_base.update({
        "fleet.replay_shards": 2,
        "replay.capacity": 8_000,          # 100 blocks -> 50/shard
        "replay.learning_starts": 400,
    })
    spill_cells = {}
    for label, spill in (("spill_off", 0), ("spill_on", 50)):
        ov = dict(svc_base)
        ov["fleet.spill_blocks"] = spill
        spill_cells[label] = run_e2e(
            min(seconds, 30.0), envs_per_actor=lanes_per_actor,
            num_actors=num_actors, overrides=ov, actor_mode="thread")
    out["spill_off"] = spill_cells["spill_off"]
    out["spill_on"] = spill_cells["spill_on"]
    if spill_cells["spill_off"]["learner_steps_per_sec"] > 0:
        out["learner_steps_ratio_spill"] = round(
            spill_cells["spill_on"]["learner_steps_per_sec"]
            / spill_cells["spill_off"]["learner_steps_per_sec"], 3)
    sp = ((spill_cells["spill_on"].get("replay_service") or {})
          .get("spill") or {})
    out["spill_occupancy"] = sp.get("occupancy")
    out["spill_hit_rate"] = sp.get("hit_rate")
    out["spill_capacity"] = sp.get("capacity")
    return out


def _synth_service_blocks(spec, n: int, seed: int = 0) -> list:
    """Synthetic filled block records at ``spec``'s exact layout (the
    socket/spill cells need wire-shaped payloads, not real episodes):
    positive priorities so the sampled tree is well-formed, stamped
    learning steps so the accountant advances."""
    from r2d2_tpu.replay.structs import Block, empty_block_np
    rng = np.random.default_rng(seed)
    proto = empty_block_np(spec)
    blocks = []
    for i in range(n):
        fields = {k: v.copy() for k, v in proto.items()}
        fields["priority"] = np.abs(rng.normal(
            1.0, 0.5, spec.seqs_per_block)).astype(np.float32) + 1e-3
        fields["learning_steps"] = np.full(
            (spec.seqs_per_block,), spec.learning, np.int32)
        fields["num_sequences"] = np.asarray(spec.seqs_per_block, np.int32)
        fields["weight_version"] = np.asarray(i, np.int32)
        blocks.append(Block(**fields))
    return blocks


def run_service_ingest_ab(seconds: float, overrides: Optional[dict] = None,
                          repeats: int = 3, num_actors: int = 4,
                          lanes_per_actor: int = 4,
                          ingest_blocks: int = 8,
                          socket_window: int = 4) -> dict:
    """Batched/pipelined service data-plane A/B (ISSUE 16 acceptance),
    three cells in one artifact:

      * **socket rung** — an in-proc ReplayService behind its TCP
        server, one remote producer pushing a fixed synthetic block
        budget: per-block lockstep frames (PR 15's rung) vs stacked
        windowed frames (one ``addw`` frame per group of
        ``ingest_blocks``, ``socket_window`` unacked frames in flight)
        into a grouped-ingest service. ABBA-interleaved ``repeats``
        with per-arm medians; ``socket_speedup`` is the >= 1.3x
        headline (frame count and ack round-trips both drop ~Kx).
      * **e2e arms** — the SAME service-routed thread-mode system
        (``fleet.replay_shards=2``) at ``fleet.ingest_batch_blocks``
        1 vs ``ingest_blocks``: ``learner_steps_ratio_ingest`` bounds
        the grouped commit plane's cost on the training path (>= 0.98
        acceptance).
      * **spill prefetch** — a populated spill tier sampled under
        inline promotion vs the async priority-ordered prefetch
        (``fleet.spill_prefetch``): median sample-path latency per arm;
        ``prefetch_sample_speedup`` >= 1 means moving promotion off the
        sample path never cost latency."""
    from r2d2_tpu.fleet.replay_service import (RemoteReplayProducer,
                                               ReplayService,
                                               ReplayServiceServer)
    from r2d2_tpu.replay.structs import ReplaySpec

    base = dict(overrides or {})
    out: dict = {}

    # -- socket-rung producer cell ---------------------------------------
    spec = ReplaySpec(
        num_blocks=64, seqs_per_block=4, block_length=20, burn_in=4,
        learning=5, forward=3, frame_stack=2, frame_height=12,
        frame_width=12, hidden_dim=16, batch_size=16, prio_exponent=0.9,
        is_exponent=0.6, replay_diag=False)
    n_blocks = 192
    blocks = _synth_service_blocks(spec, n_blocks)
    cells = {"per_block": [], "batched": []}

    def socket_arm(batched: bool) -> float:
        svc = ReplayService(spec, 2, ingest_batch_blocks=(
            ingest_blocks if batched else 1))
        server = ReplayServiceServer(svc)
        producer = RemoteReplayProducer(
            server.host, server.port,
            window=(socket_window if batched else 1))
        try:
            t0 = time.perf_counter()
            if batched:
                for i in range(0, n_blocks, ingest_blocks):
                    producer.add_blocks(blocks[i:i + ingest_blocks])
                producer.flush()
            else:
                for blk in blocks:
                    producer.add_block(blk)
            dt = time.perf_counter() - t0
            assert server.blocks_received == n_blocks
            return n_blocks / dt
        finally:
            producer.close()
            server.close()

    # One untimed pass per arm first: the grouped arm's first run pays the
    # replay_add_many AOT chunk compiles and the per-block arm pays the
    # replay_add jit — neither belongs in a timed cell.
    socket_arm(False)
    socket_arm(True)
    for rep in range(max(repeats, 1)):
        order = (("per_block", False), ("batched", True))
        if rep % 2:
            order = order[::-1]    # ABBA: cancel monotonic host drift
        for label, batched in order:
            cells[label].append(socket_arm(batched))
    med_off = float(np.median(cells["per_block"]))
    med_on = float(np.median(cells["batched"]))
    out["socket_rung"] = {
        "blocks": n_blocks, "group": ingest_blocks,
        "window": socket_window, "repeats": max(repeats, 1),
        "per_block_blocks_per_sec_cells": [round(v, 1)
                                           for v in cells["per_block"]],
        "batched_blocks_per_sec_cells": [round(v, 1)
                                         for v in cells["batched"]],
        "per_block_blocks_per_sec": round(med_off, 1),
        "batched_blocks_per_sec": round(med_on, 1),
    }
    if med_off > 0:
        out["socket_speedup"] = round(med_on / med_off, 3)

    # -- e2e arms: grouped commit plane on the real learner path ---------
    svc_base = dict(base)
    svc_base.update({
        "fleet.replay_shards": 2,
        "replay.capacity": 8_000,          # 100 blocks -> 50/shard
        "replay.learning_starts": 400,
    })
    e2e_cells = {"ingest_off": [], "ingest_on": []}
    for rep in range(max(repeats - 1, 1)):
        order = (("ingest_off", 1), ("ingest_on", ingest_blocks))
        if rep % 2:
            order = order[::-1]
        for label, k in order:
            ov = dict(svc_base)
            ov["fleet.ingest_batch_blocks"] = k
            e2e_cells[label].append(run_e2e(
                min(seconds, 30.0), envs_per_actor=lanes_per_actor,
                num_actors=num_actors, overrides=ov, actor_mode="thread"))
    out["ingest_off"] = e2e_cells["ingest_off"][-1]
    out["ingest_on"] = e2e_cells["ingest_on"][-1]
    out["learner_steps_per_sec_cells"] = {
        k: [c["learner_steps_per_sec"] for c in v]
        for k, v in e2e_cells.items()}

    def med(label):
        return float(np.median(
            [c["learner_steps_per_sec"] for c in e2e_cells[label]]))

    if med("ingest_off") > 0:
        out["learner_steps_ratio_ingest"] = round(
            med("ingest_on") / med("ingest_off"), 3)
    ingest_tel = (out["ingest_on"].get("replay_service") or {}).get(
        "ingest") or {}
    out["ingest_blocks_per_dispatch"] = ingest_tel.get("blocks_per_dispatch")

    # -- spill prefetch: sample-path latency, inline vs async ------------
    def prefetch_arm(prefetch: bool) -> float:
        svc = ReplayService(spec, 1, spill_blocks=64, promote_per_sample=1,
                            spill_prefetch=prefetch)
        try:
            for blk in _synth_service_blocks(spec, 128, seed=7):
                svc.add_block(blk)       # 64 demoted into the tier
            import jax
            key = jax.random.PRNGKey(0)
            lat = []
            for _ in range(40):
                key, sub = jax.random.split(key)
                t0 = time.perf_counter()
                batch, shard, _snap = svc.sample(sub)
                jax.block_until_ready(batch.obs)
                lat.append(time.perf_counter() - t0)
                svc.update_priorities(
                    shard, batch.idxes,
                    np.ones(spec.batch_size, np.float32))
            svc.drain_prefetch()
            return float(np.median(lat))
        finally:
            svc.close()

    inline_s = prefetch_arm(False)
    prefetch_s = prefetch_arm(True)
    out["spill_prefetch"] = {
        "inline_sample_ms": round(inline_s * 1e3, 3),
        "prefetch_sample_ms": round(prefetch_s * 1e3, 3),
    }
    if prefetch_s > 0:
        out["prefetch_sample_speedup"] = round(inline_s / prefetch_s, 3)
    return out


def serve_latency_probe(seconds: float, clients: int,
                        overrides: Optional[dict] = None) -> dict:
    """Pure serving-plane cell: one in-proc PolicyServer, ``clients``
    pipelined lanes stepping synthetic frames as fast as replies come
    back. Measures the micro-batcher itself — batch fill, client-visible
    latency percentiles, forward time — without a training loop
    competing for the cores. ``slo_ok`` is the acceptance leg: latency
    p99 <= serve.deadline_ms + the same run's forward p99."""
    import jax

    from r2d2_tpu.models.network import NetworkApply
    from r2d2_tpu.serve import (InprocEndpoint, PolicyServer,
                                RemoteBatchedPolicy, ServingStats)
    from r2d2_tpu.telemetry import Telemetry
    ov = dict(E2E_CPU_OVERRIDES)
    ov.update(overrides or {})
    ov.pop("actor.inference", None)
    cfg = _bench_config(ov)
    net = NetworkApply(6, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    params = net.init(jax.random.PRNGKey(0))
    stats = ServingStats()
    telemetry = Telemetry(name="serve-probe")
    ep = InprocEndpoint()
    srv = PolicyServer(cfg, net, params, endpoint=ep, stats=stats,
                       telemetry=telemetry, client_timed=True).start()
    try:
        remote = RemoteBatchedPolicy(
            ep.connect(), net.action_dim, [0.05] * clients,
            list(range(clients)), stats=stats,
            timeout_s=cfg.serve.request_timeout_s)
        rng = np.random.default_rng(0)
        h, w = cfg.env.frame_height, cfg.env.frame_width
        frames = rng.integers(0, 255, (64, h, w), np.uint8)
        for i in range(clients):
            remote.observe_reset_lane(i, frames[i % 64])
        for _ in range(3):                       # warm the round trip
            remote.act()
        stats.interval_block()                   # drop warm-up samples
        telemetry.timers.take()
        ticks = 0
        t0 = time.time()
        while time.time() - t0 < seconds:
            actions, _, _ = remote.act()
            remote.observe(frames[(ticks + np.arange(clients)) % 64],
                           actions)
            ticks += 1
        elapsed = time.time() - t0
        block = stats.interval_block() or {}
        from r2d2_tpu.telemetry.core import summarize_matrix
        stages = summarize_matrix(telemetry.timers.take())
        fwd = stages.get("serve/forward") or {}
        lat = block.get("latency") or {}
        cell = {
            "clients": clients,
            "seconds": round(elapsed, 1),
            "ticks": ticks,
            "requests_per_sec": round(ticks * clients / elapsed, 1),
            "fill_mean": (block.get("batch") or {}).get("fill_mean"),
            "fill_p99": (block.get("batch") or {}).get("fill_p99"),
            "latency_p50_ms": lat.get("p50_ms"),
            "latency_p99_ms": lat.get("p99_ms"),
            "forward_p50_ms": fwd.get("p50_ms"),
            "forward_p99_ms": fwd.get("p99_ms"),
            "deadline_ms": cfg.serve.deadline_ms,
        }
        if lat.get("p99_ms") is not None and fwd.get("p99_ms") is not None:
            cell["slo_ok"] = bool(
                lat["p99_ms"] <= cfg.serve.deadline_ms + fwd["p99_ms"])
        return cell
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# Sharded serving fleet A/B (ISSUE 17): scaling curve + brownout anatomy.
#
# This container exposes ONE core, so N REAL server forwards cannot
# overlap — a real-compute scaling cell would measure GIL arbitration,
# not the fleet. The scaling cells therefore run TIMED-FORWARD
# EMULATION: the real jitted forward is calibrated once per dispatch
# bucket (median of repeated runs), then each emulated server's forward
# is a GIL-releasing sleep of the calibrated time returning zeros. What
# stays REAL: the whole serving plane around the forward — routing,
# micro-batching, cache leases, admission, reply paths. Parity/failover
# correctness runs with REAL forwards in tests/test_serve.py.


def _calibrate_forward_table(cfg, net, params, buckets,
                             repeats: int = 5) -> dict:
    """Median real single-forward latency per pow2 dispatch bucket —
    the timed-forward emulation's lookup table (seconds per bucket)."""
    from r2d2_tpu.actor.policy import make_forward_fn
    fwd = make_forward_fn(net)
    h, w, s = net.obs_hw
    hd = net.config.hidden_dim
    table = {}
    for b in sorted(set(int(x) for x in buckets)):
        args = (params, np.zeros((b, h, w, s), np.float32),
                np.zeros(b, np.int32), np.zeros((b, 2, hd), np.float32))
        np.asarray(fwd(*args)[0])            # compile outside the timing
        ts = []
        for _ in range(max(repeats, 1)):
            t0 = time.perf_counter()
            np.asarray(fwd(*args)[0])
            ts.append(time.perf_counter() - t0)
        table[b] = float(np.median(ts))
    return table


def serve_fleet_probe(seconds: float, servers: int, clients: int,
                      overrides: Optional[dict] = None,
                      forward_table: Optional[dict] = None,
                      max_batch: Optional[int] = None,
                      queue_depth_bound: int = 0) -> dict:
    """One serving-fleet cell: ``servers`` in-proc server loops behind
    the client-side router, ``clients`` pipelined lanes stepping
    synthetic frames as fast as replies come back. ``state_shards`` is
    set to the client count so contiguous client ids spread EVENLY over
    the servers (each lane its own shard group); per-server
    ``max_batch`` defaults to the per-server lane share so a full
    micro-batch dispatches without waiting out the deadline. With
    ``forward_table`` the forward is the calibrated sleep stand-in (see
    the section comment); without it the real forward runs (parity-true
    but meaningless for N>1 scaling on one core).

    The scaling cells pass an EXPLICIT ``max_batch`` = clients /
    max-fleet-width so every arm forwards the same batch shape and the
    arms differ only in how many of those equal batches run at once:
    the single server drains the client tick as max-width sequential
    dispatches, four servers overlap them exactly as N accelerator
    hosts would. (Letting each arm batch its full per-server share
    instead would fold the CPU calibration's strong batch sublinearity
    — a host artifact; accelerators at serving batch sizes are
    latency-bound — into the fleet curve.)"""
    import jax

    from r2d2_tpu.models.network import NetworkApply
    from r2d2_tpu.serve import (RemoteBatchedPolicy, ServerFleet,
                                ServingStats)
    shards = max(clients, servers)
    mb = max_batch if max_batch is not None else max(
        1, clients // servers)
    ov = dict(overrides or {})
    ov.pop("actor.inference", None)
    ov.update({
        "serve.servers": servers, "serve.max_servers": servers,
        "serve.state_shards": shards, "serve.state_slots": 64 * shards,
        "serve.max_batch": mb,
        "serve.queue_depth_bound": queue_depth_bound,
    })
    cfg = _bench_config(ov)
    net = NetworkApply(6, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    params = net.init(jax.random.PRNGKey(0))
    hd = cfg.network.hidden_dim
    fff = None
    if forward_table is not None:
        biggest = max(forward_table)

        def fff(slot, _t=forward_table, _big=biggest):
            def fwd(params, stacked, last_action, hidden):
                b = int(stacked.shape[0])
                time.sleep(_t.get(b, _t[_big]))
                return (np.zeros(b, np.int64),
                        np.zeros((b, 6), np.float32),
                        np.zeros((b, 2, hd), np.float32))
            return fwd
    stats = ServingStats()
    fleet = ServerFleet(cfg, net, params, stats=stats, client_timed=True,
                        forward_fn_factory=fff)
    try:
        remote = RemoteBatchedPolicy(
            fleet.connect(), net.action_dim, [0.05] * clients,
            list(range(clients)), stats=stats,
            timeout_s=cfg.serve.request_timeout_s)
        rng = np.random.default_rng(0)
        h, w = cfg.env.frame_height, cfg.env.frame_width
        frames = rng.integers(0, 255, (64, h, w), np.uint8)
        for i in range(clients):
            remote.observe_reset_lane(i, frames[i % 64])
        for _ in range(3):                       # warm the round trip
            remote.act()
        fleet.interval_block()                   # drop warm-up samples
        ticks = 0
        t0 = time.time()
        while time.time() - t0 < seconds:
            actions, _, _ = remote.act()
            remote.observe(frames[(ticks + np.arange(clients)) % 64],
                           actions)
            ticks += 1
        elapsed = time.time() - t0
        block = fleet.interval_block() or {}
        lat = block.get("latency") or {}
        adm = block.get("admission") or {}
        alat = adm.get("admitted_latency") or {}
        cell = {
            "servers": servers,
            "clients": clients,
            "max_batch": mb,
            "queue_depth_bound": queue_depth_bound,
            "emulated_forward": forward_table is not None,
            "seconds": round(elapsed, 1),
            "ticks": ticks,
            # logical client steps/s — shed retries do NOT count, so
            # this is goodput, the number the scaling gate reads
            "requests_per_sec": round(ticks * clients / elapsed, 1),
            "fill_mean": (block.get("batch") or {}).get("fill_mean"),
            "latency_p50_ms": lat.get("p50_ms"),
            "latency_p99_ms": lat.get("p99_ms"),
            "admitted_p50_ms": alat.get("p50_ms"),
            "admitted_p99_ms": alat.get("p99_ms"),
            "shed": adm.get("shed", 0),
            "shed_frac": adm.get("shed_frac", 0.0),
            "client_shed_retries": remote.shed_retries,
            "server_rows": len((block.get("servers") or {})
                               .get("rows") or {}),
        }
        return cell
    finally:
        fleet.stop()


def socket_rt_probe(seconds: float,
                    overrides: Optional[dict] = None) -> dict:
    """Socket-transport round-trip re-quote (TCP_NODELAY satellite):
    one real-forward server behind the TCP loopback transport, ONE
    blocking client — the per-request wire latency with Nagle disabled
    on both sides, comparable against PERF.md's earlier socket quotes."""
    import jax

    from r2d2_tpu.models.network import NetworkApply
    from r2d2_tpu.serve import (InprocEndpoint, PolicyServer, RemotePolicy,
                                ServingStats, SocketChannel,
                                SocketServerTransport)
    ov = dict(E2E_CPU_OVERRIDES)
    ov.update(overrides or {})
    ov.pop("actor.inference", None)
    cfg = _bench_config(ov)
    net = NetworkApply(6, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    params = net.init(jax.random.PRNGKey(0))
    stats = ServingStats()
    ep = InprocEndpoint()
    srv = PolicyServer(cfg, net, params, endpoint=ep, stats=stats,
                       client_timed=True).start()
    transport = SocketServerTransport(ep.submit, cfg.serve.host, 0)
    try:
        remote = RemotePolicy(
            SocketChannel(transport.host, transport.port),
            net.action_dim, 0.05, stats=stats,
            timeout_s=cfg.serve.request_timeout_s)
        rng = np.random.default_rng(0)
        h, w = cfg.env.frame_height, cfg.env.frame_width
        frame = rng.integers(0, 255, (h, w), np.uint8)
        remote.observe_reset(frame)
        for _ in range(5):
            remote.act()
        lats = []
        t0 = time.time()
        while time.time() - t0 < seconds:
            t1 = time.perf_counter()
            action, _, _ = remote.act()
            lats.append(time.perf_counter() - t1)
            remote.observe(frame, action)
        arr = np.asarray(lats) * 1e3
        return {
            "round_trips": len(lats),
            "rt_p50_ms": round(float(np.percentile(arr, 50)), 3),
            "rt_p95_ms": round(float(np.percentile(arr, 95)), 3),
            "rt_p99_ms": round(float(np.percentile(arr, 99)), 3),
            "tcp_nodelay": True,
        }
    finally:
        transport.close()
        srv.stop()


def run_serve_fleet_ab(seconds: float, overrides: Optional[dict] = None,
                       repeats: int = 2,
                       servers_sweep: Tuple[int, ...] = (1, 2, 4),
                       clients_sweep: Tuple[int, ...] = (8, 16)) -> dict:
    """Serving-fleet scaling A/B (ISSUE 17 acceptance), one artifact:

      * **scaling curve** — requests/s at 1/2/4 emulated server loops x
        client widths, ABBA-interleaved ``repeats`` times with per-arm
        medians; the gate is 4 servers >= 2.5x single-server goodput at
        the widest EQUAL client count. ``max_batch`` is pinned to
        clients / max-fleet-width in EVERY arm (equal batch shape;
        serve_fleet_probe's docstring argues why), so the arms differ
        only in how many of those batches forward concurrently. A
        ``single_server_full_batch`` cell (1 server batching its whole
        client share at once) rides along as the transparency baseline
        for the CPU table's batch sublinearity.
      * **brownout anatomy** — single server at 2x-overload (clients =
        2x max_batch), bound off vs on: with ``queue_depth_bound`` the
        overflow sheds (retry-after; clients back off and retry) while
        ADMITTED p99 stays within the SLO (deadline + 2 service times);
        unbounded, the same offered load queues and the client-visible
        p99 inflates past it.
      * **socket round trip** — the TCP_NODELAY re-quote cell.

    The forward calibration table (real jitted forward, median per pow2
    bucket, at the REFERENCE network shape so per-row compute dominates
    dispatch overhead) ships in the artifact."""
    base = dict(overrides or {})
    cmax = max(clients_sweep)
    # reference-shape network for calibration + scaling cells: on a tiny
    # net the fixed dispatch overhead flattens fwd(C)/fwd(C/4) and the
    # cell would measure overhead, not scaling headroom
    import jax

    from r2d2_tpu.models.network import NetworkApply
    cal_cfg = _bench_config(base)
    cal_net = NetworkApply(6, cal_cfg.network, cal_cfg.env.frame_stack,
                           cal_cfg.env.frame_height,
                           cal_cfg.env.frame_width)
    cal_params = cal_net.init(jax.random.PRNGKey(0))
    buckets = []
    b = 1
    while b <= cmax:
        buckets.append(b)
        b *= 2
    table = _calibrate_forward_table(cal_cfg, cal_net, cal_params, buckets)
    out = {
        "repeats": max(repeats, 1),
        "forward_table_ms": {str(k): round(v * 1e3, 3)
                             for k, v in sorted(table.items())},
        "emulation": "timed-forward (calibrated sleep; see PERF.md)",
    }

    width = max(servers_sweep)
    cells = {}
    for rep in range(max(repeats, 1)):
        arms = list(servers_sweep)
        if rep % 2:
            arms = arms[::-1]      # ABBA: cancel monotonic host drift
        for c in clients_sweep:
            for s in arms:
                if c < s or c % width:
                    continue
                cells.setdefault((s, c), []).append(serve_fleet_probe(
                    seconds, s, c, overrides=base, forward_table=table,
                    max_batch=max(1, c // width)))
    out["scaling"] = [
        {**runs[-1],
         "requests_per_sec": float(np.median(
             [r["requests_per_sec"] for r in runs])),
         "requests_per_sec_cells": [r["requests_per_sec"] for r in runs]}
        for (s, c), runs in sorted(cells.items())]

    def med_rps(s, c):
        runs = cells.get((s, c))
        return (float(np.median([r["requests_per_sec"] for r in runs]))
                if runs else None)

    hi, lo = max(servers_sweep), min(servers_sweep)
    if med_rps(lo, cmax):
        out["fleet_scaling_ratio"] = round(
            med_rps(hi, cmax) / med_rps(lo, cmax), 3)
        out["fleet_scaling_servers"] = [lo, hi]
        out["fleet_scaling_clients"] = cmax
    # transparency baseline: one server batching its FULL client share
    # (best single-server batch shape; folds the CPU table's batch
    # sublinearity back in — see serve_fleet_probe's docstring)
    out["single_server_full_batch"] = serve_fleet_probe(
        seconds, 1, cmax, overrides=base, forward_table=table,
        max_batch=cmax)

    # brownout anatomy: ONE server, offered load 2x its micro-batch
    # capacity; the bound is HALF a batch deep (the shed pass runs after
    # each batch fill and rejects only the overflow past the bound, so a
    # bound >= max_batch under exactly-2x load never triggers)
    mb = cmax // 2
    over = {k: v for k, v in base.items()}
    unbounded = serve_fleet_probe(seconds, 1, cmax, overrides=over,
                                  forward_table=table, max_batch=mb,
                                  queue_depth_bound=0)
    bounded = serve_fleet_probe(seconds, 1, cmax, overrides=over,
                                forward_table=table, max_batch=mb,
                                queue_depth_bound=max(1, mb // 2))
    svc_ms = table[mb] * 1e3
    slo_ms = _bench_config(base).serve.deadline_ms + 2.0 * svc_ms
    out["brownout"] = {
        "overload_factor": 2.0,
        "max_batch": mb,
        "service_ms": round(svc_ms, 3),
        "slo_ms": round(slo_ms, 3),
        "unbounded": unbounded,
        "bounded": bounded,
    }
    out["brownout_shed_frac"] = bounded["shed_frac"]
    if bounded.get("admitted_p99_ms") is not None:
        out["brownout_admitted_p99_ms"] = bounded["admitted_p99_ms"]
        out["brownout_ok"] = bool(
            bounded["shed_frac"] > 0.0
            and bounded["admitted_p99_ms"] <= slo_ms)
        # regress-gated form of the brownout acceptance: emitted ONLY
        # while the bounded arm actually sheds, so the metric VANISHES
        # (a gate failure) if brownout stops triggering, and its value
        # drops below 1.0 exactly when admitted p99 exceeds the SLO.
        if bounded["shed_frac"] > 0.0:
            out["brownout_slo_headroom_ratio"] = round(
                slo_ms / bounded["admitted_p99_ms"], 3)

    out["socket_rt"] = socket_rt_probe(min(seconds, 10.0), overrides=base)
    return out


def run_fleet_mh(seconds: float, envs_per_actor: int = 8,
                 dp: int = 2, fleet_on: bool = True,
                 overrides: Optional[dict] = None) -> dict:
    """One lockstep-trainer cell for the fleet A/B: the rank-aware
    ``train_multihost`` loop run as a SINGLE controller over an emulated
    dp-wide mesh (this container's CPU backend has no multiprocess
    collectives — known since PR 3 — so the in-artifact A/B measures the
    fleet plane's per-iteration cost where it lives: the widened psum
    row, the per-iteration timers, the gauge readback, and the rank-0
    aggregator; the loopback two-process twin is the slow-marked test).
    Thread actors feed the real lockstep ingest + dp-sharded learner
    step; speeds come from the rank-0 TrainMetrics records exactly like
    ``run_e2e``."""
    from r2d2_tpu.parallel.multihost import train_multihost

    ov = dict(E2E_CPU_OVERRIDES)
    ov.update({"actor.num_actors": 1,
               "actor.envs_per_actor": envs_per_actor,
               "mesh.dp": dp,
               "telemetry.fleet_enabled": bool(fleet_on)})
    ov.update(overrides or {})
    scratch = None
    if "runtime.save_dir" not in ov:
        import tempfile
        scratch = tempfile.mkdtemp(prefix="r2d2_fleet_")
        ov["runtime.save_dir"] = scratch
    cfg = _bench_config(ov)
    records = []
    t0 = time.time()
    try:
        out = train_multihost(cfg, max_training_steps=10**9,
                              max_seconds=seconds, actor_mode="thread",
                              log_fn=records.append)
    finally:
        if scratch is not None:
            import shutil
            shutil.rmtree(scratch, ignore_errors=True)
    elapsed = time.time() - t0
    steady = [r for r in records[1:] if r.get("training_speed")]
    env_speed = (float(np.mean([r["buffer_speed"] for r in steady]))
                 if steady else 0.0)
    train_speed = (float(np.mean([r["training_speed"] for r in steady]))
                   if steady else 0.0)
    fleet = next((r["fleet"] for r in reversed(records)
                  if r.get("fleet")), None)
    return {
        "seconds": round(elapsed, 1),
        "dp": dp,
        "fleet_enabled": bool(fleet_on),
        "total_env_steps": int(out["env_steps"]),
        "total_train_steps": int(out["step"]),
        "env_steps_per_sec": round(env_speed, 1),
        "learner_steps_per_sec": round(train_speed, 2),
        "env_steps_per_sec_overall": round(out["env_steps"] / elapsed, 1),
        "learner_steps_per_sec_overall": round(out["step"] / elapsed, 2),
        "records": len(records),
        "fleet": fleet,
        "config": {k: ov[k] for k in sorted(ov)},
    }


def run_fleet_ab(seconds: float, envs_per_actor: int = 8, dp: int = 2,
                 overrides: Optional[dict] = None,
                 repeats: int = 2) -> dict:
    """Fleet-observability overhead A/B (ISSUE 12 acceptance): the SAME
    lockstep trainer with ``telemetry.fleet_enabled`` on vs off, in one
    artifact. Budget under test: the fleet plane — the widened psum row
    (one f32 per dp row + the gauge reductions/all-gathers riding the
    existing dispatch), per-iteration perf_counter pairs, the gauge-table
    readback, the rank-0 FleetAggregator flush, and the rank-0 host row
    — costs < 2% on BOTH env-steps/s and learner updates/s (the
    established pillar budget). Cells run INTERLEAVED off/on ``repeats``
    times with per-arm medians (the learning/resources-AB noise
    treatment). The ON cells carry the ``fleet`` block (per-rank
    step-time table, wait fraction, straggler rank) as end-to-end
    evidence; the OFF cells prove the records carried no ``fleet`` key
    (the kill-switch schema contract)."""
    cells = {"fleet_off": [], "fleet_on": []}
    for rep in range(max(repeats, 1)):
        order = (("fleet_off", False), ("fleet_on", True))
        if rep % 2:
            # ABBA order: repeated in-process runs on a small shared
            # host drift slower over time (cache/alloc pressure), and a
            # fixed A,B order would hand the whole drift to one arm —
            # alternating cancels the linear component in the medians
            order = order[::-1]
        for label, on in order:
            cells[label].append(run_fleet_mh(
                seconds, envs_per_actor, dp=dp, fleet_on=on,
                overrides=overrides))

    def med(label, key):
        return float(np.median([c[key] for c in cells[label]]))

    out = {"fleet_off": cells["fleet_off"][-1],
           "fleet_on": cells["fleet_on"][-1],
           "repeats": max(repeats, 1),
           "dp": dp,
           "env_steps_per_sec_cells": {
               k: [c["env_steps_per_sec"] for c in v]
               for k, v in cells.items()},
           "learner_steps_per_sec_cells": {
               k: [c["learner_steps_per_sec"] for c in v]
               for k, v in cells.items()}}
    if med("fleet_off", "env_steps_per_sec") > 0:
        ratio = (med("fleet_on", "env_steps_per_sec")
                 / med("fleet_off", "env_steps_per_sec"))
        out["env_steps_ratio"] = round(ratio, 3)
        out["overhead_pct"] = round((1.0 - ratio) * 100.0, 2)
    if med("fleet_off", "learner_steps_per_sec") > 0:
        out["learner_steps_ratio"] = round(
            med("fleet_on", "learner_steps_per_sec")
            / med("fleet_off", "learner_steps_per_sec"), 3)
    fb = next((c["fleet"] for c in reversed(cells["fleet_on"])
               if c.get("fleet")), None)
    out["fleet_block_on"] = bool(fb)
    if fb:
        out["wait_frac_on"] = (fb.get("lockstep") or {}).get("wait_frac")
        out["step_time_on"] = fb.get("step_time")
    out["fleet_block_off"] = any(c.get("fleet")
                                 for c in cells["fleet_off"])
    return out


# Anakin A/B shape: the acting-path STRUCTURAL overhead measurement. The
# policy/env compute is shrunk until it is nearly free on this host (8px
# frames, hidden 16, one conv), because the quantity under test is the
# host-boundary cost per env step — interpreter round-trips, per-tick jit
# dispatch, numpy rolls, LocalBuffer appends, queue hops — which the fused
# on-device path removes. The host arm's floor is ~3 ms of that per-step
# host work per 16-lane tick REGARDLESS of shape, so shrinking compute
# isolates the structural term. Both arms run the IDENTICAL config except
# the routing knobs. On the shared-silicon CPU container the fused arm is
# still bounded by the same 2 cores that run the host arm's policy, which
# caps the measurable ratio (see PERF.md "On-device acting"); on a TPU the
# acting scan runs on accelerator silicon the host actor cannot use at
# all, which is where the Podracer-class orders-of-magnitude appear.
ANAKIN_AB_OVERRIDES = {
    "env.frame_height": 8, "env.frame_width": 8,
    "env.frame_stack": 2, "env.episode_len": 200,
    "network.hidden_dim": 16, "network.cnn_out_dim": 16,
    "network.conv_layers": ((4, 4, 4),),
    # exact first-conv rewrite (models/network.py, parity-tested): on this
    # CPU the 2-input-channel conv is the fused scan's hottest op and the
    # s2d layout runs it ~25% faster; identical math in BOTH arms
    "network.space_to_depth": True,
    "sequence.burn_in_steps": 8, "sequence.learning_steps": 5,
    "sequence.forward_steps": 3,
    # capacity = anakin lanes x block_length: the ring must hold one full
    # segment (one block per lane); kept identical in BOTH arms — ring
    # size shapes the learner's compile/sample cost, so it is part of the
    # matched config, which also caps the lane count at 1024
    "replay.block_length": 200, "replay.capacity": 204_800,
    "replay.batch_size": 8, "replay.learning_starts": 1_000,
    "runtime.save_interval": 0, "runtime.log_interval": 2.0,
}


def run_anakin_ab(seconds: float, envs_per_actor: int = 16,
                  anakin_lanes: int = 512,
                  overrides: Optional[dict] = None,
                  repeats: int = 2) -> dict:
    """On-device acting A/B (ISSUE 6 acceptance): the host-vector actor
    system vs the fused Anakin loop, same config, one artifact.

    Three cells:
      * ``host_vector``   — the legacy system: one process actor with
        ``envs_per_actor`` lanes feeding the learner through the shm ring
        (the PR1-era architecture at this shape);
      * ``anakin``        — ``actor.on_device`` with ``anakin_lanes``
        lanes, unthrottled (acting-rate headline);
      * ``anakin_balanced`` — the fused loop rate-limited to a
        collect:learn ratio that matches the host arm's learner cadence,
        showing the SAME loop trains at full learner speed while still
        collecting several times faster than the host arm.

    Arms run INTERLEAVED ``repeats`` times and the headline ratios come
    from per-arm medians, for the same reason ``run_learning_ab`` does:
    single cells swing ±10% on the shared 2-core host, which is noise at
    the ~62x acting headline but material for the ~1.3x balanced learner
    ratio. Every cell's speeds stay in the artifact.

    The headline ``env_steps_ratio`` is anakin / host_vector."""
    base = dict(ANAKIN_AB_OVERRIDES)
    base.update(overrides or {})
    anakin_ov = dict(base)
    anakin_ov.update({"actor.on_device": True,
                      "actor.anakin_lanes": anakin_lanes})
    bal_ov = dict(base)
    bal_ov.update({"actor.on_device": True,
                   "actor.anakin_lanes": max(anakin_lanes // 2, 1),
                   "replay.max_env_steps_per_train_step": 1024.0})
    cells = {"host_vector": [], "anakin": [], "anakin_balanced": []}
    for _ in range(max(repeats, 1)):
        cells["host_vector"].append(
            run_e2e(seconds, envs_per_actor=envs_per_actor, num_actors=1,
                    overrides=dict(base)))
        cells["anakin"].append(run_e2e(seconds, overrides=dict(anakin_ov)))
        cells["anakin_balanced"].append(
            run_e2e(seconds, overrides=dict(bal_ov)))

    def med(label, key):
        return float(np.median([c[key] for c in cells[label]]))

    out = {label: runs[-1] for label, runs in cells.items()}
    out["repeats"] = max(repeats, 1)
    out["env_steps_per_sec_cells"] = {
        k: [c["env_steps_per_sec"] for c in v] for k, v in cells.items()}
    out["learner_steps_per_sec_cells"] = {
        k: [c["learner_steps_per_sec"] for c in v] for k, v in cells.items()}
    host_env = med("host_vector", "env_steps_per_sec")
    if host_env > 0:
        out["env_steps_ratio"] = round(
            med("anakin", "env_steps_per_sec") / host_env, 2)
        out["env_steps_ratio_balanced"] = round(
            med("anakin_balanced", "env_steps_per_sec") / host_env, 2)
    host_lr = med("host_vector", "learner_steps_per_sec")
    if host_lr > 0:
        out["learner_steps_ratio_balanced"] = round(
            med("anakin_balanced", "learner_steps_per_sec") / host_lr, 3)
    return out


def run_sharded_anakin_ab(seconds: float, anakin_lanes: int = 1024,
                          dp: int = 2, overrides: Optional[dict] = None,
                          repeats: int = 3) -> dict:
    """Sharded-anakin scaling A/B (ISSUE 8 acceptance): the fused
    act+train loop on a 1x1 mesh vs the IDENTICAL config on a dp-wide
    mesh — same ``anakin_lanes`` total, partitioned into per-shard lane
    groups acting into their local replay shards while the learner runs
    its dp-sharded step on the same mesh. Three cells:

      * ``anakin_dp1``     — actor.on_device at ``anakin_lanes`` on
        mesh.dp=1 (the PR6 fused loop at the same total lane count);
      * ``anakin_sharded`` — the same lanes on mesh.dp=``dp``
        (``anakin_lanes/dp`` per shard);
      * ``anakin_dp1_half_lanes`` — mesh.dp=1 at ``anakin_lanes/dp``
        lanes, i.e. ONE shard's group on one device: the strongest
        single-mesh reference (a lone fused program tops out near this
        lane count — growing it past the cache-friendly width REGRESSES
        per-step cost, which is exactly why scaling continues through
        shards, not lanes), and the honest denominator for the
        weak-scaling reading.

    The headline ``env_steps_ratio_sharded`` compares the equal-lane
    arms; ``env_steps_ratio_sharded_vs_half`` quotes the sharded arm
    against the half-lane single-mesh reference so the scaling claim
    can never hide behind an oversized dp=1 denominator. On CPU the
    mesh is emulated
    (``XLA_FLAGS=--xla_force_host_platform_device_count=N``, which
    ``main`` sets automatically when it owns the process); the claim
    under test — aggregate env-steps/s scaling with dp at
    equal-or-better learner updates/s — carries to real chips, where
    each shard owns its own silicon. Arms run INTERLEAVED ``repeats``
    times with per-arm medians (the run_learning_ab noise treatment);
    every cell's speeds stay in the artifact."""
    import jax
    if len(jax.devices()) < dp:
        raise SystemExit(
            f"--sharded-anakin-ab needs >= {dp} devices but only "
            f"{len(jax.devices())} are visible; on CPU run with "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={dp} "
            "(python -m r2d2_tpu.tools.e2e_bench sets this itself when "
            "launched as the main program)")
    base = dict(ANAKIN_AB_OVERRIDES)
    base.update({"actor.on_device": True,
                 "actor.anakin_lanes": anakin_lanes})
    base.update(overrides or {})
    dp1_ov = dict(base, **{"mesh.dp": 1})
    dpn_ov = dict(base, **{"mesh.dp": dp})
    half_ov = dict(base, **{"mesh.dp": 1,
                            "actor.anakin_lanes": anakin_lanes // dp})
    cells = {"anakin_dp1": [], "anakin_sharded": [],
             "anakin_dp1_half_lanes": []}
    for _ in range(max(repeats, 1)):
        cells["anakin_dp1"].append(run_e2e(seconds, overrides=dict(dp1_ov)))
        cells["anakin_sharded"].append(
            run_e2e(seconds, overrides=dict(dpn_ov)))
        cells["anakin_dp1_half_lanes"].append(
            run_e2e(seconds, overrides=dict(half_ov)))

    def med(label, key):
        return float(np.median([c[key] for c in cells[label]]))

    out = {label: runs[-1] for label, runs in cells.items()}
    out["dp"] = dp
    out["anakin_lanes"] = anakin_lanes
    out["repeats"] = max(repeats, 1)
    out["env_steps_per_sec_cells"] = {
        k: [c["env_steps_per_sec"] for c in v] for k, v in cells.items()}
    out["learner_steps_per_sec_cells"] = {
        k: [c["learner_steps_per_sec"] for c in v] for k, v in cells.items()}
    out["dp1_env_steps_per_sec"] = round(
        med("anakin_dp1", "env_steps_per_sec"), 1)
    out["sharded_env_steps_per_sec"] = round(
        med("anakin_sharded", "env_steps_per_sec"), 1)
    out["dp1_learner_steps_per_sec"] = round(
        med("anakin_dp1", "learner_steps_per_sec"), 2)
    out["sharded_learner_steps_per_sec"] = round(
        med("anakin_sharded", "learner_steps_per_sec"), 2)
    out["half_lanes_env_steps_per_sec"] = round(
        med("anakin_dp1_half_lanes", "env_steps_per_sec"), 1)
    if out["dp1_env_steps_per_sec"] > 0:
        out["env_steps_ratio_sharded"] = round(
            out["sharded_env_steps_per_sec"]
            / out["dp1_env_steps_per_sec"], 3)
    if out["dp1_learner_steps_per_sec"] > 0:
        out["learner_steps_ratio_sharded"] = round(
            out["sharded_learner_steps_per_sec"]
            / out["dp1_learner_steps_per_sec"], 3)
    if out["half_lanes_env_steps_per_sec"] > 0:
        out["env_steps_ratio_sharded_vs_half"] = round(
            out["sharded_env_steps_per_sec"]
            / out["half_lanes_env_steps_per_sec"], 3)
    return out


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--sweep", default="1,4,16",
                   help="comma-separated envs_per_actor cells (actor phase)")
    p.add_argument("--seconds", type=float, default=5.0,
                   help="measurement window per actor-sweep cell")
    p.add_argument("--e2e-seconds", type=float, default=60.0,
                   help="end-to-end actors+learner window (0 disables)")
    p.add_argument("--envs-per-actor", type=int, default=16,
                   help="lanes per actor in the e2e phase")
    p.add_argument("--num-actors", type=int, default=1)
    p.add_argument("--ingest-ab", type=int, default=1,
                   help="1 (default): run the e2e phase as an ingestion A/B"
                        " — batched+pipelined (replay.ingest_batch_blocks ="
                        " --ingest-batch-blocks) vs the per-block path, one"
                        " artifact; 0: single e2e run at the config default")
    p.add_argument("--ingest-batch-blocks", type=int, default=8,
                   help="K for the A/B's batched cell")
    p.add_argument("--anakin-ab", type=int, default=0,
                   help="1: run the e2e phase as the on-device acting A/B "
                        "instead — host-vector actor system vs the fused "
                        "Anakin act+train loop at the structural-overhead "
                        "shape (ANAKIN_AB_OVERRIDES), one artifact with "
                        "env-steps/s and learner updates/s per arm")
    p.add_argument("--anakin-lanes", type=int, default=512,
                   help="batched env lanes for the A/B's on-device cell "
                        "(512 is this host's steps/s sweet spot; raise "
                        "replay.capacity via --override when raising this "
                        "past capacity/block_length)")
    p.add_argument("--sharded-anakin-ab", type=int, default=0,
                   help="1: run the e2e phase as the sharded-anakin "
                        "scaling A/B instead — the fused act+train loop "
                        "at --sharded-lanes on mesh.dp=1 vs the SAME "
                        "lanes partitioned across a --sharded-dp mesh "
                        "(CPU: emulated devices, forced automatically), "
                        "plus a half-lane dp=1 reference arm, one "
                        "artifact with per-arm medians and the "
                        "env/learner scaling ratios")
    p.add_argument("--sharded-dp", type=int, default=2,
                   help="mesh width for the sharded-anakin A/B's dp arm")
    p.add_argument("--sharded-lanes", type=int, default=1024,
                   help="TOTAL lanes for the sharded-anakin A/B (both "
                        "main arms; the reference arm runs half) — "
                        "divisible by --sharded-dp, and the FULL count "
                        "must stay <= capacity/block_length (the "
                        "equal-lane dp=1 arm holds all of them on one "
                        "ring; raise replay.capacity via --override "
                        "when raising this)")
    p.add_argument("--telemetry-ab", type=int, default=0,
                   help="1: run the e2e phase as a telemetry on/off A/B "
                        "instead (overhead budget < 2%% env-steps/s; one "
                        "artifact with both cells + the ON cell's stage "
                        "percentiles)")
    p.add_argument("--learning-ab", type=int, default=0,
                   help="1: run the e2e phase as a learning-diagnostics "
                        "on/off A/B instead (telemetry.learning_enabled; "
                        "budget < 2%% on env-steps/s AND learner "
                        "updates/s; the ON cell carries the 'learning' "
                        "block as end-to-end evidence)")
    p.add_argument("--replay-diag-ab", type=int, default=0,
                   help="1: run the e2e phase as a replay-diagnostics "
                        "on/off A/B instead (telemetry.replay_diag_enabled;"
                        " budget < 2%% on env-steps/s AND learner "
                        "updates/s; interleaved repeats with per-arm "
                        "medians, the ON cells carry the 'replay_diag' "
                        "block, plus one sharded (emulated dp=2) anakin "
                        "evidence cell with per-shard + merged sum-tree "
                        "views)")
    p.add_argument("--fleet-ab", type=int, default=0,
                   help="1: run the e2e phase as the fleet-observability "
                        "on/off A/B instead (telemetry.fleet_enabled; the "
                        "lockstep multihost trainer as one controller "
                        "over an emulated --sharded-dp mesh; budget < 2%% "
                        "on env-steps/s AND learner updates/s; "
                        "interleaved repeats with per-arm medians, the "
                        "ON cells carry the 'fleet' block as evidence)")
    p.add_argument("--serve-ab", type=int, default=0,
                   help="1: run the e2e phase as the policy-serving A/B "
                        "instead (ISSUE 13) — thread-mode actors with "
                        "actor.inference local vs server at equal lanes "
                        "(ABBA-interleaved, per-arm medians) plus a "
                        "1/4/16 client-count sweep showing batch fill "
                        "climbing with load; one artifact with the "
                        "serving block (latency percentiles, fill) as "
                        "evidence")
    p.add_argument("--serve-lanes", type=int, default=16,
                   help="lanes (= serve clients) for the serve A/B's "
                        "equal-lane arms")
    p.add_argument("--quant-ab", type=int, default=0,
                   help="1: run the e2e phase as the quantized-inference "
                        "A/B instead (ISSUE 14) — thread-mode acting arm "
                        "at network.inference_dtype f32 vs int8 "
                        "(ABBA-interleaved, per-arm medians, the int8 "
                        "cells carry the 'quant' accuracy block) + a "
                        "serving-probe arm at both dtypes + the analytic "
                        "weight-bytes table (the >= 3x int8 cut); one "
                        "artifact (E2E_r16.json)")
    p.add_argument("--service-ingest-ab", type=int, default=0,
                   help="1: run the e2e phase as the batched service "
                        "data-plane A/B instead (ISSUE 16) — socket-rung "
                        "producer cell (per-block lockstep vs stacked "
                        "windowed frames, ABBA medians, the >= 1.3x "
                        "headline), service-routed learner at "
                        "fleet.ingest_batch_blocks 1 vs "
                        "--ingest-batch-blocks (updates/s ratio >= 0.98), "
                        "and the spill-prefetch sample-latency pair; one "
                        "artifact (E2E_r18.json)")
    p.add_argument("--socket-window", type=int, default=4,
                   help="in-flight frame bound for the service-ingest "
                        "A/B's windowed arm (fleet.socket_window)")
    p.add_argument("--elastic-ab", type=int, default=0,
                   help="1: run the e2e phase as the elastic-fleet A/B "
                        "instead (ISSUE 15) — fixed vs churned fleet at "
                        "equal lanes (grammar-injected leave@block + "
                        "join@t re-adoption under fleet.elastic; the "
                        "learner must never stall) plus a spill-tier "
                        "on/off pair on the service-routed learner "
                        "(fleet.replay_shards=2, 2x-capacity spill); "
                        "one artifact (E2E_r17.json)")
    p.add_argument("--serve-fleet-ab", type=int, default=0,
                   help="1: run the e2e phase as the serving-fleet "
                        "scaling A/B instead (ISSUE 17) — 1/2/4 emulated "
                        "server loops x client widths on the client-side "
                        "router (timed-forward emulation, calibrated per "
                        "dispatch bucket; ABBA-interleaved, per-arm "
                        "medians; 4-server >= 2.5x goodput gate), the "
                        "2x-overload brownout pair (queue_depth_bound "
                        "off/on; admitted p99 within SLO while shedding) "
                        "and the TCP_NODELAY socket round-trip re-quote; "
                        "one artifact (E2E_r19.json)")
    p.add_argument("--recovery-ab", type=int, default=0,
                   help="run the crash-recovery overhead A/B instead "
                        "(ISSUE 18): runtime.snapshot_interval on vs "
                        "off on the same e2e system — the durable "
                        "replay snapshot plane must cost < 2%% on both "
                        "env-steps/s and learner updates/s, the ON "
                        "cells must carry the recovery block, the OFF "
                        "cells must not")
    p.add_argument("--snapshot-interval", type=int, default=200,
                   help="--recovery-ab: the ON arm's snapshot cadence "
                        "in learner steps (default models the ~30s "
                        "loss window the kill drills assert; the write "
                        "duty cycle, not the on-path capture, is the "
                        "cost, so overhead scales ~1/interval)")
    p.add_argument("--tracing-ab", type=int, default=0,
                   help="1: run the e2e phase as the cross-plane tracing "
                        "on/off A/B instead (ISSUE 19: "
                        "telemetry.tracing_enabled; budget <= 2%% on "
                        "env-steps/s AND learner updates/s; ABBA-"
                        "interleaved repeats with per-arm medians; the "
                        "ON cells carry the 'trace' block — sampled "
                        "rows, the env-step->gradient e2e latency "
                        "histogram, per-hop breakdown — as end-to-end "
                        "evidence; one artifact, E2E_r21.json)")
    p.add_argument("--promotion-ab", type=int, default=0,
                   help="1: run the e2e phase as the policy-quality "
                        "on/off A/B instead (ISSUE 20: "
                        "telemetry.quality_enabled; budget <= 2%% on "
                        "env-steps/s AND learner updates/s; ABBA-"
                        "interleaved repeats with per-arm medians in "
                        "thread mode so the calibration tap rides the "
                        "acting hot path; the ON cells carry the "
                        "'quality' block, the OFF cells none; plus the "
                        "gated-canary promotion drill as the evidence "
                        "cell; one artifact, E2E_r22.json)")
    p.add_argument("--resources-ab", type=int, default=0,
                   help="1: run the e2e phase as a resource/compile/alerts "
                        "on/off A/B instead (telemetry.resources_enabled; "
                        "budget < 2%% on env-steps/s AND learner "
                        "updates/s; the ON cells carry the 'resources' "
                        "block + alert tally as end-to-end evidence)")
    p.add_argument("--ab-repeats", type=int, default=2,
                   help="interleaved off/on pairs for the learning A/B "
                        "(medians per arm; small-host noise control)")
    p.add_argument("--out", default=os.environ.get("R2D2_E2E_OUT", ""),
                   help="also write the JSON artifact to this path")
    p.add_argument("--override", action="append", default=[],
                   help="dotted config override key=value (repeatable)")
    args = p.parse_args(argv)

    if args.sharded_anakin_ab or args.replay_diag_ab or args.fleet_ab:
        # the emulated-mesh recipe (README "On-device acting"): the CPU
        # platform must present >= dp devices BEFORE the backend
        # initializes — harmless on real accelerators (the flag only
        # shapes the host platform). argparse runs first so this can
        # land before the jax import below. The replay-diag A/B needs it
        # for its sharded-anakin evidence cell; the fleet A/B for its
        # emulated dp-wide lockstep mesh.
        from r2d2_tpu.utils.platform import force_host_device_count
        force_host_device_count(max(args.sharded_dp, 2))
    from r2d2_tpu.utils import pin_platform
    pin_platform()
    import jax

    overrides = {}
    for ov in args.override:
        k, _, v = ov.partition("=")
        try:
            overrides[k] = json.loads(v)
        except (json.JSONDecodeError, ValueError):
            overrides[k] = v

    dev = jax.devices()[0]
    out = {"metric": "e2e_throughput", "platform": dev.platform,
           "device_kind": dev.device_kind,
           "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
    sweep = [int(x) for x in args.sweep.split(",") if x]
    if sweep:
        out["actor_sweep"] = run_actor_sweep(sweep, seconds=args.seconds,
                                             overrides=overrides)
    if args.e2e_seconds > 0:
        if args.sharded_anakin_ab:
            out["e2e_sharded_anakin_ab"] = run_sharded_anakin_ab(
                args.e2e_seconds, anakin_lanes=args.sharded_lanes,
                dp=args.sharded_dp, overrides=overrides,
                repeats=args.ab_repeats)
        elif args.anakin_ab:
            out["e2e_anakin_ab"] = run_anakin_ab(
                args.e2e_seconds, args.envs_per_actor,
                anakin_lanes=args.anakin_lanes, overrides=overrides,
                repeats=args.ab_repeats)
        elif args.fleet_ab:
            out["e2e_fleet_ab"] = run_fleet_ab(
                args.e2e_seconds, args.envs_per_actor,
                dp=args.sharded_dp, overrides=overrides,
                repeats=args.ab_repeats)
        elif args.service_ingest_ab:
            out["e2e_service_ingest_ab"] = run_service_ingest_ab(
                args.e2e_seconds, overrides=overrides,
                repeats=args.ab_repeats,
                ingest_blocks=args.ingest_batch_blocks,
                socket_window=args.socket_window)
        elif args.serve_fleet_ab:
            out["e2e_serve_fleet_ab"] = run_serve_fleet_ab(
                args.e2e_seconds, overrides=overrides,
                repeats=args.ab_repeats)
        elif args.elastic_ab:
            out["e2e_elastic_ab"] = run_elastic_ab(
                args.e2e_seconds, overrides=overrides,
                repeats=args.ab_repeats)
        elif args.quant_ab:
            out["e2e_quant_ab"] = run_quant_ab(
                args.e2e_seconds, lanes=args.serve_lanes,
                overrides=overrides, repeats=args.ab_repeats)
        elif args.serve_ab:
            out["e2e_serve_ab"] = run_serve_ab(
                args.e2e_seconds, lanes=args.serve_lanes,
                overrides=overrides, repeats=args.ab_repeats)
        elif args.replay_diag_ab:
            out["e2e_replay_diag_ab"] = run_replay_diag_ab(
                args.e2e_seconds, args.envs_per_actor, args.num_actors,
                overrides=overrides, repeats=args.ab_repeats,
                sharded_dp=args.sharded_dp)
        elif args.recovery_ab:
            out["recovery_ab"] = run_recovery_ab(
                args.e2e_seconds, args.envs_per_actor, args.num_actors,
                overrides=overrides, repeats=args.ab_repeats,
                snapshot_interval=args.snapshot_interval)
        elif args.promotion_ab:
            out["e2e_promotion_ab"] = run_promotion_ab(
                args.e2e_seconds, args.envs_per_actor, args.num_actors,
                overrides=overrides, repeats=args.ab_repeats)
        elif args.tracing_ab:
            out["e2e_tracing_ab"] = run_tracing_ab(
                args.e2e_seconds, args.envs_per_actor, args.num_actors,
                overrides=overrides, repeats=args.ab_repeats)
        elif args.resources_ab:
            out["e2e_resources_ab"] = run_resources_ab(
                args.e2e_seconds, args.envs_per_actor, args.num_actors,
                overrides=overrides, repeats=args.ab_repeats)
        elif args.learning_ab:
            out["e2e_learning_ab"] = run_learning_ab(
                args.e2e_seconds, args.envs_per_actor, args.num_actors,
                overrides=overrides, repeats=args.ab_repeats)
        elif args.telemetry_ab:
            out["e2e_telemetry_ab"] = run_telemetry_ab(
                args.e2e_seconds, args.envs_per_actor, args.num_actors,
                overrides=overrides)
        elif args.ingest_ab:
            out["e2e_ingest_ab"] = run_ingest_ab(
                args.e2e_seconds, args.envs_per_actor, args.num_actors,
                args.ingest_batch_blocks, overrides=overrides)
        else:
            out["e2e"] = run_e2e(args.e2e_seconds, args.envs_per_actor,
                                 args.num_actors, overrides=overrides)
    line = json.dumps(out)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
