"""One-command profiling of the fused learner step (SURVEY §5.1).

Captures a ``jax.profiler`` trace of N fused train steps on synthetic
replay at the configured scale, then aggregates the Chrome-trace events
per execution plane — the per-op device-time attribution that drove every
round-3/4 optimization decision (PERF.md), as a reproducible tool instead
of a by-hand analysis. The reference has no profiling hooks at all; its
GPU time is opaque outside nvprof runs it never scripts.

    python -m r2d2_tpu.cli.profile --steps 20 --out /tmp/r2d2_prof

On TPU the summary's interesting plane is ``/device:TPU:0`` (XLA op
spans); on CPU only the host plane exists (python dispatch) — the tool
reports whatever planes the backend emitted.
"""

import glob
import gzip
import json
import os
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

from r2d2_tpu.config import Config

PlaneSummary = List[Tuple[str, float, int]]   # (name, total_us, count)


def capture_step_trace(cfg: Config, steps: int, out_dir: str,
                       warmup: int = 3) -> str:
    """Run ``steps`` fused learner steps (resolved defaults: decode/gather
    kernels, bf16, steps_per_dispatch) under a profiler trace; returns
    ``out_dir``. Replay is filled with synthetic blocks at the configured
    shapes, so no actors/envs are involved — this profiles the learner
    alone, like bench.py."""
    import jax
    import numpy as np

    from r2d2_tpu.learner import create_train_state, make_learner_step
    from r2d2_tpu.learner.train_step import make_multi_learner_step
    from r2d2_tpu.models import NetworkApply
    from r2d2_tpu.parallel.dryrun import _synthetic_block
    from r2d2_tpu.replay import ReplaySpec, replay_add, replay_init

    spec = ReplaySpec.from_config(cfg)
    action_dim = 18
    net = NetworkApply(action_dim, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    ts = create_train_state(jax.random.PRNGKey(1), net, cfg.optim)
    rs = replay_init(spec)
    rng = np.random.default_rng(0)
    # enough blocks that stratified sampling has real spread; bounded so
    # setup stays cheap at big configured capacities
    for _ in range(min(spec.num_blocks, 8)):
        rs = replay_add(spec, rs, _synthetic_block(spec, rng))

    k = cfg.runtime.resolved_steps_per_dispatch()
    if k > 1:
        step = make_multi_learner_step(net, spec, cfg.optim,
                                       cfg.network.use_double, k)
    else:
        step = make_learner_step(net, spec, cfg.optim, cfg.network.use_double)

    for _ in range(warmup):                      # compile outside the trace
        ts, rs, m = step(ts, rs)
    jax.block_until_ready(m["loss"])

    # whole dispatches only: the ACTUAL traced step count is
    # dispatches * k, which can exceed the request — recorded in the
    # metadata file so ms/step always divides by what really ran
    dispatches = -(-max(1, steps) // k)
    traced_steps = dispatches * k
    # shared capture lifecycle (telemetry/profiler.py): the trace stops
    # exactly once even when a step raises mid-capture — the same helper
    # the orchestrator's first-interval/profile_at_step/SIGUSR2 captures
    # run on
    from r2d2_tpu.telemetry.profiler import trace
    with trace(out_dir):
        for _ in range(dispatches):
            ts, rs, m = step(ts, rs)
        jax.block_until_ready(m["loss"])
    with open(os.path.join(out_dir, "profile_meta.json"), "w") as f:
        json.dump({"steps": traced_steps, "steps_per_dispatch": k,
                   "batch_size": spec.batch_size}, f)
    return out_dir


def traced_step_count(trace_dir: str) -> Optional[int]:
    """The step count recorded by capture_step_trace, or None for traces
    captured elsewhere."""
    try:
        with open(os.path.join(trace_dir, "profile_meta.json")) as f:
            return int(json.load(f)["steps"])
    except (OSError, KeyError, ValueError):
        return None


def summarize_trace(trace_dir: str, top: int = 25
                    ) -> Dict[str, PlaneSummary]:
    """Aggregate the newest Chrome trace under ``trace_dir``: per execution
    plane (pid), total duration and count of every complete ('X') event,
    sorted by total time. Spans can overlap (these are NOT exclusive
    occupancy numbers — same caveat as PERF.md's round-3 analysis)."""
    paths = sorted(glob.glob(os.path.join(
        trace_dir, "**", "*.trace.json.gz"), recursive=True),
        key=os.path.getmtime)
    if not paths:
        raise FileNotFoundError(
            f"no *.trace.json.gz under {trace_dir!r} — did the capture run?")
    with gzip.open(paths[-1], "rt") as f:
        events = json.load(f)["traceEvents"]

    plane_names: Dict[int, str] = {}
    totals: Dict[int, Dict[str, List[float]]] = defaultdict(
        lambda: defaultdict(lambda: [0.0, 0]))
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            plane_names[e["pid"]] = e["args"]["name"]
        elif e.get("ph") == "X":
            t = totals[e["pid"]][e["name"]]
            t[0] += float(e.get("dur", 0.0))
            t[1] += 1
    out: Dict[str, PlaneSummary] = {}
    for pid, names in totals.items():
        plane = plane_names.get(pid, f"pid{pid}")
        rows = sorted(((n, d, int(c)) for n, (d, c) in names.items()),
                      key=lambda r: -r[1])
        out[plane] = rows[:top]
    return out


def device_plane(summary: Dict[str, PlaneSummary]
                 ) -> Optional[Tuple[str, PlaneSummary]]:
    """The accelerator plane of a summary, if one exists."""
    for plane, rows in summary.items():
        if "/device:" in plane and "CPU" not in plane:
            return plane, rows
    return None


def format_summary(summary: Dict[str, PlaneSummary], steps: int) -> str:
    lines = []
    for plane, rows in sorted(summary.items()):
        lines.append(f"== {plane} (top {len(rows)} by total span; spans "
                     "overlap — not exclusive occupancy) ==")
        for name, us, count in rows:
            lines.append(f"  {us/1e3:10.3f} ms  x{count:<6d} "
                         f"{us/1e3/max(steps,1):8.4f} ms/step  {name[:90]}")
    return "\n".join(lines)
