"""Genetic / population-based hyperparameter search.

The reference keeps this on a separate ``genetic`` branch (not in the
snapshot) driven by the ``<-- GEN`` tags in config.py
(/root/reference/README.md:13,28-32, config.py:12-57). Here it is a
first-class tool over ``GENETIC_SEARCH_SPACE`` (r2d2_tpu/config.py), whose
entries are layout-safe by construction: continuous fields carry (lo, hi)
ranges (optionally log-scaled), constrained fields carry explicit choices, so
every sampled genome builds a valid Config.

Generic over the fitness function: pass any ``eval_fn(Config) -> float``
(e.g. mean episode return of a short training slice — see cli/genetic.py).
"""

import math
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from r2d2_tpu.config import Config, GENETIC_SEARCH_SPACE

Genome = Dict[str, Any]


def sample_gene(rng: np.random.Generator, spec: Dict[str, Any]) -> Any:
    if "choices" in spec:
        return spec["choices"][int(rng.integers(len(spec["choices"])))]
    lo, hi = spec["range"]
    if spec.get("log"):
        return float(np.exp(rng.uniform(math.log(lo), math.log(hi))))
    return float(rng.uniform(lo, hi))


def sample_genome(rng: np.random.Generator,
                  space: Optional[Dict[str, Dict]] = None) -> Genome:
    space = space or GENETIC_SEARCH_SPACE
    return {key: sample_gene(rng, spec) for key, spec in space.items()}


def mutate(rng: np.random.Generator, genome: Genome, rate: float = 0.25,
           space: Optional[Dict[str, Dict]] = None) -> Genome:
    """Resample each gene with probability ``rate``; continuous genes take a
    log/linear perturbation instead of a full resample half the time."""
    space = space or GENETIC_SEARCH_SPACE
    out = dict(genome)
    for key, spec in space.items():
        if rng.random() >= rate:
            continue
        if "choices" in spec or rng.random() < 0.5:
            out[key] = sample_gene(rng, spec)
        else:
            lo, hi = spec["range"]
            if spec.get("log"):
                out[key] = float(np.clip(
                    out[key] * np.exp(rng.normal(0, 0.3)), lo, hi))
            else:
                out[key] = float(np.clip(
                    out[key] + rng.normal(0, 0.15 * (hi - lo)), lo, hi))
    return out


def crossover(rng: np.random.Generator, a: Genome, b: Genome) -> Genome:
    return {k: (a[k] if rng.random() < 0.5 else b[k]) for k in a}


def genome_to_config(base: Config, genome: Genome) -> Config:
    # int-typed fields arrive as floats from perturbation; coerce by field type
    import dataclasses
    coerced = {}
    for key, value in genome.items():
        section, fname = key.split(".")
        f = {x.name: x for x in dataclasses.fields(getattr(base, section))}[fname]
        if f.type == "int":
            value = int(round(value))
        elif f.type == "bool":
            value = bool(value)
        coerced[key] = value
    return base.replace(**coerced)


@dataclass
class GenerationResult:
    genomes: List[Genome]
    fitnesses: List[float]

    @property
    def best(self) -> Tuple[Genome, float]:
        i = int(np.argmax(self.fitnesses))
        return self.genomes[i], self.fitnesses[i]


def run_search(eval_fn: Callable[[Config], float], *, base: Optional[Config] = None,
               population: int = 8, generations: int = 4, elite_frac: float = 0.25,
               mutation_rate: float = 0.25, seed: int = 0,
               space: Optional[Dict[str, Dict]] = None,
               log_fn: Optional[Callable[[int, GenerationResult], None]] = None
               ) -> List[GenerationResult]:
    """Elitist GA: keep the top ``elite_frac``, refill by crossover of two
    elites + mutation. Returns per-generation results (last one's ``best`` is
    the answer)."""
    rng = np.random.default_rng(seed)
    base = base or Config()
    space = space or GENETIC_SEARCH_SPACE
    genomes = [sample_genome(rng, space) for _ in range(population)]
    history: List[GenerationResult] = []
    n_elite = max(1, int(population * elite_frac))

    def score(g: Genome) -> Tuple[float, bool]:
        # A genome can be invalid against a user-overridden base (the space
        # is layout-safe only against the defaults — e.g. learning_steps=16
        # vs an overridden block_length=20): score it -inf instead of
        # killing the whole search at Config construction. Returns
        # (fitness, was_invalid) so an ALL-invalid generation can still
        # fail loudly below (an eval_fn -inf, e.g. a slice with no
        # episodes, is legitimate and must not trigger that).
        try:
            cfg = genome_to_config(base, g)
        except ValueError as e:
            import logging
            logging.getLogger(__name__).warning(
                "genome invalid against the base config (%s); fitness -inf", e)
            return float("-inf"), True
        return float(eval_fn(cfg)), False

    for gen in range(generations):
        scored = [score(g) for g in genomes]
        fitnesses = [f for f, _ in scored]
        if all(invalid for _, invalid in scored):
            raise ValueError(
                f"every genome in generation {gen} is invalid against the "
                "base config — the overridden base conflicts with the whole "
                "search space; relax the overrides or pass a custom space")
        result = GenerationResult(genomes, fitnesses)
        history.append(result)
        if log_fn:
            log_fn(gen, result)
        order = np.argsort(fitnesses)[::-1]
        elites = [genomes[i] for i in order[:n_elite]]
        children = []
        while len(children) < population - n_elite:
            a, b = rng.choice(n_elite, 2, replace=True)
            children.append(
                mutate(rng, crossover(rng, elites[a], elites[b]),
                       mutation_rate, space))
        genomes = elites + children
    return history
