"""Alerting sentinel CLI: evaluate the declarative rule set over a
metrics stream, offline or live (ISSUE 7).

The in-run engine (telemetry/alerts.py) rides ``TrainMetrics.log`` — every
periodic record carries an ``alerts`` block and firings append to
``alerts_player{p}.jsonl``. This tool is the same engine pointed at the
FILES, for the two cases the in-run engine cannot serve:

  * **post-mortem / pre-PR7 streams** (``--replay``, the default): replay
    an existing ``metrics_player{p}.jsonl`` through a FRESH engine and
    print every firing — triage a finished or crashed run, or a run that
    predates the pillar / ran with it kill-switched. Exit code 1 when any
    ``crit`` rule fired, so a soak wrapper can gate on it.
  * **live watch** (``--follow``): tail the stream and evaluate records
    as they land — a sentinel process beside a run whose in-run engine is
    disabled (or whose save_dir you can only read).

Rule bounds come from the same ``telemetry.alerts_*`` knobs the run uses,
overridable per flag-less dotted ``--override key=value`` pairs (e.g.
``telemetry.alerts_retrace_storm=5``). ``--rules`` prints the effective
rule table and exits.

Fleet streams (ISSUE 12): ``--host-rank R`` points the SAME engine
(replay or --follow) at a rank's ``telemetry_host{R}.jsonl`` — host rows
share the record line format, so the resource/compile/fleet rules
evaluate unchanged (throughput rules stay inactive; those metrics only
exist on rank 0's record). ``--alerts-stream PATH`` instead
replays/tails an existing alerts JSONL (``alerts_player{p}.jsonl`` or a
rank's ``alerts_host{r}.jsonl``) — no re-evaluation, just the firing
log with the same crit exit code, for triaging a rank whose metrics
stream rotated away.

Per-process plane streams (ISSUE 19): ``--stream PATH`` points the
engine at an arbitrary metrics-format JSONL — the serving fleet's
``serve_metrics.jsonl`` or a standalone ReplayService's
``service_metrics_p{p}.jsonl`` — replayed or tailed (``--follow``)
exactly like the player stream; their ``serving`` / ``replay_service``
blocks sit at the same record paths, so the plane rules evaluate
unchanged. The quality ledger's ``quality_player{p}.jsonl`` (ISSUE 20)
is the same shape again — each row carries a ``proc`` identity header
with a clock anchor plus the ``quality`` block at its in-run record
path, so the ``quality_regression`` / ``canary_divergence`` /
``promotion_stall`` rules evaluate against it directly.

    python -m r2d2_tpu.tools.sentinel --dir models                # replay
    python -m r2d2_tpu.tools.sentinel --dir models --follow       # live
    python -m r2d2_tpu.tools.sentinel --dir models --host-rank 1
    python -m r2d2_tpu.tools.sentinel --alerts-stream models/alerts_host1.jsonl
    python -m r2d2_tpu.tools.sentinel --rules
"""

import json
import os
import sys
import time


def build_engine(overrides=None, jsonl_path=None, resume=True):
    """A fresh AlertEngine on the stock rule set, bounds from the default
    TelemetryConfig plus dotted overrides — exactly what an in-run engine
    would have used at those knob values. ``resume=True`` (the CLI
    default) APPENDS to ``jsonl_path``: pointing --out at a run's live
    ``alerts_player{p}.jsonl`` must merge, never wipe, its history."""
    from r2d2_tpu.config import Config
    from r2d2_tpu.telemetry import AlertEngine, default_rules
    cfg = Config().replace(**(overrides or {}))
    return AlertEngine(default_rules(cfg.telemetry), jsonl_path=jsonl_path,
                       resume=resume)


def replay_stream(records, engine, emit=print) -> dict:
    """Run every record through the engine; returns a summary dict
    ({"records", "fired", "crit", "by_rule"}) and emits one line per
    firing."""
    fired_total = 0
    crit = 0
    by_rule = {}
    for record in records:
        block = engine.evaluate(record)
        for alert in block["fired"]:
            fired_total += 1
            by_rule[alert["rule"]] = by_rule.get(alert["rule"], 0) + 1
            if alert.get("severity") == "crit":
                crit += 1
            emit(f"t={record.get('t', 0):8.1f}s step="
                 f"{record.get('training_steps', 0):>8} "
                 f"{alert.get('severity', '?'):>4} {alert['rule']}"
                 + (f" value={alert['value']:.4g}"
                    if alert.get("value") is not None else "")
                 + (f" bound={alert.get('bound')}" if "bound" in alert
                    else "")
                 + (f" baseline={alert['baseline']:.4g}"
                    if alert.get("baseline") is not None else ""))
    return {"records": len(records), "fired": fired_total, "crit": crit,
            "by_rule": by_rule}


def resume_after_shrink(path: str, seen: int):
    """A followed stream SHRANK: distinguish size-cap rotation (the
    fleet plane's RotatingJsonlWriter moved the live file to ``.1`` —
    the SAME run continuing, so rule state must survive and the rotated
    generation's unread tail must still be evaluated) from a fresh-run
    truncation (new run: reset the engine). Returns ``(is_rotation,
    backlog_rows)`` — on rotation the backlog is the old generation's
    rows past ``seen``; on truncation it is empty and the caller
    rebuilds the engine."""
    from r2d2_tpu.tools.logparse import parse_jsonl
    try:
        rotated = parse_jsonl(path + ".1")
    except FileNotFoundError:
        rotated = []
    if len(rotated) >= seen > 0:
        return True, rotated[seen:]
    return False, []


def replay_alerts_stream(path: str, follow: bool = False,
                         interval: float = 2.0, emit=print) -> int:
    """Replay (or tail) an existing alerts JSONL — the machine-readable
    side a run's engine already wrote (alerts_player{p}.jsonl, or a
    rank's alerts_host{r}.jsonl under the fleet plane). No rules are
    re-evaluated; exit 1 when the stream carries any crit firing."""
    from r2d2_tpu.tools.logparse import parse_jsonl

    def show(rows):
        crit = 0
        for row in rows:
            if row.get("severity") == "crit":
                crit += 1
            emit(f"t={row.get('t') or 0:8.1f}s step="
                 f"{row.get('training_steps') or 0:>8} "
                 f"{row.get('severity', '?'):>4} {row.get('rule')}"
                 + (f" value={row['value']:.4g}"
                    if row.get("value") is not None else ""))
        return crit

    if not follow:
        try:
            rows = parse_jsonl(path)
        except FileNotFoundError:
            print(f"no alerts stream at {path}", file=sys.stderr)
            return 2
        crit = show(rows)
        print(f"-- {len(rows)} firing(s), {crit} crit")
        return 1 if crit else 0

    seen = 0
    while True:
        try:
            rows = parse_jsonl(path)
        except FileNotFoundError:
            rows = []
            print(f"waiting for {path} ...")
        if len(rows) < seen:      # truncation: fresh run, restart the tail
            seen = 0
        if len(rows) > seen:
            show(rows[seen:])
            seen = len(rows)
        time.sleep(interval)


def main(argv=None) -> int:
    import argparse

    from r2d2_tpu.tools.logparse import parse_jsonl

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--dir", default="models",
                   help="the run's save_dir (metrics_player{p}.jsonl)")
    p.add_argument("--player", type=int, default=0)
    p.add_argument("--follow", action="store_true",
                   help="tail the stream and evaluate records as they land")
    p.add_argument("--interval", type=float, default=2.0,
                   help="poll cadence in follow mode")
    p.add_argument("--out", default="",
                   help="also append firings to this alerts JSONL "
                        "(existing history is kept, never truncated)")
    p.add_argument("--rules", action="store_true",
                   help="print the effective rule table and exit")
    p.add_argument("--host-rank", type=int, default=None,
                   help="evaluate a rank's telemetry_host{R}.jsonl host-row "
                        "stream instead of the player metrics stream "
                        "(replay and --follow both work)")
    p.add_argument("--stream", default="",
                   help="replay/tail an ARBITRARY metrics-format JSONL "
                        "through the engine instead of the player stream "
                        "— the per-process rows the serve fleet "
                        "(serve_metrics.jsonl), a standalone "
                        "ReplayService (service_metrics_p{p}.jsonl), and "
                        "the quality ledger (quality_player{p}.jsonl) "
                        "write (ISSUEs 19/20); their blocks sit at the "
                        "same record paths, so the serving / "
                        "replay_service / quality rules evaluate "
                        "unchanged")
    p.add_argument("--alerts-stream", default="",
                   help="replay/tail an existing alerts JSONL "
                        "(alerts_player{p}.jsonl or alerts_host{r}.jsonl) "
                        "instead of evaluating a metrics stream; exit 1 "
                        "when it contains a crit firing")
    p.add_argument("--override", action="append", default=[],
                   help="dotted config override key=value (repeatable), "
                        "e.g. telemetry.alerts_retrace_storm=5")
    args = p.parse_args(argv)

    overrides = {}
    for ov in args.override:
        k, _, v = ov.partition("=")
        try:
            overrides[k] = json.loads(v)
        except (json.JSONDecodeError, ValueError):
            overrides[k] = v

    engine = build_engine(overrides, jsonl_path=args.out or None)
    if args.rules:
        print(f"{'rule':<24}{'kind':<11}{'severity':<9}{'bound':>10}  path")
        for r in engine.rules:
            print(f"{r.name:<24}{r.kind:<11}{r.severity:<9}"
                  f"{r.bound:>10}  {'.'.join(r.path)}"
                  + (" (below)" if r.below else ""))
        return 0

    if args.alerts_stream:
        return replay_alerts_stream(args.alerts_stream, args.follow,
                                    args.interval)

    if args.stream:
        path = args.stream
    elif args.host_rank is not None:
        path = os.path.join(args.dir,
                            f"telemetry_host{args.host_rank}.jsonl")
    else:
        path = os.path.join(args.dir, f"metrics_player{args.player}.jsonl")
    if not args.follow:
        try:
            records = parse_jsonl(path)
        except FileNotFoundError:
            print(f"no metrics stream at {path}", file=sys.stderr)
            return 2
        summary = replay_stream(records, engine)
        print(f"-- {summary['records']} records, {summary['fired']} "
              f"alert(s) ({summary['crit']} crit): "
              + (" ".join(f"{k}x{v}"
                          for k, v in sorted(summary["by_rule"].items()))
                 or "clean"))
        return 1 if summary["crit"] else 0

    seen = 0
    while True:
        try:
            records = parse_jsonl(path)
        except FileNotFoundError:
            records = []
            print(f"waiting for {path} ...")
        if len(records) < seen:
            # the stream SHRANK: either the fleet plane's size-cap
            # rotation (same run — evaluate the rotated generation's
            # unread tail, keep rule state) or a fresh (non-resume) run
            # truncating the file (reset the engine, so the old run's
            # counter baselines and median windows don't poison the new
            # one)
            rotation, backlog = resume_after_shrink(path, seen)
            if rotation:
                print(f"stream rotated ({seen} -> {len(records)} "
                      f"records), evaluating {len(backlog)} rotated "
                      "row(s)")
                replay_stream(backlog, engine)
            else:
                print(f"stream restarted ({seen} -> {len(records)} "
                      "records), resetting rule state")
                engine = build_engine(overrides,
                                      jsonl_path=args.out or None)
            seen = 0
        if len(records) > seen:
            replay_stream(records[seen:], engine)
            seen = len(records)
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
