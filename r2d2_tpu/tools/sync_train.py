"""Deterministic synchronous collect:learn training — the bit-reproducible
single-stream loop.

With free-running actor threads the collect:learn interleaving — and the
learning outcome — swings with host scheduling (measured: the same config
scored eval returns anywhere in 25-86 across identical invocations,
PERF.md). This loop removes the scheduler from the result entirely: exactly
``replay.max_env_steps_per_train_step`` env steps per learner step, one
thread, seeds pinned — the same run twice is bit-identical.

Two consumers:
  * the learnability acceptance test (tests/test_learnability.py) — the CI
    stand-in for the reference's Atari Boxing curve
    (/root/reference/README.md:38-40);
  * the genetic search's ``--fitness-mode=sync`` (cli/genetic.py) — genome
    selection on a deterministic signal instead of scheduler noise.

The threaded/process orchestrations (runtime/orchestrator.py) remain the
production path; this is the measurement instrument.
"""

from typing import Sequence, Tuple

from r2d2_tpu.config import Config


def sync_train(cfg: Config, train_steps: int, collect_eps: float,
               seed: int = 0, param_refresh_interval: int = 10,
               deadline: float = None):
    """Train ``train_steps`` learner steps with synchronous collection at
    the pinned ``replay.max_env_steps_per_train_step`` ratio (must be set
    >= 1 in ``cfg``). Returns ``(net, learner)`` with the trained state.

    Deterministic given ``(cfg, seed)``: one env, one behavior policy at
    ``collect_eps``, refreshed from the learner every
    ``param_refresh_interval`` steps. ``deadline`` (a ``time.time()``
    value) raises TimeoutError when exceeded — a wall-clock escape hatch
    for oversized configs; note a run that hits it is no longer a
    deterministic function of the config alone.
    """
    import time
    from r2d2_tpu.actor.local_buffer import LocalBuffer
    from r2d2_tpu.actor.policy import ActorPolicy
    from r2d2_tpu.envs.factory import create_env
    from r2d2_tpu.models.network import NetworkApply
    from r2d2_tpu.runtime.learner_loop import Learner

    ratio = int(cfg.replay.max_env_steps_per_train_step)
    if ratio < 1:
        raise ValueError(
            "sync_train needs replay.max_env_steps_per_train_step >= 1 "
            f"(got {cfg.replay.max_env_steps_per_train_step}) — the ratio "
            "IS the collection schedule here")
    if cfg.replay.placement != "device":
        raise ValueError(
            "sync_train requires replay.placement='device': the host "
            "placement's async prefetch/write-back threads sample "
            "concurrently with ingestion, which breaks the "
            "bit-reproducibility this loop exists to provide")
    env = create_env(cfg.env, seed=seed)
    net = NetworkApply(env.action_space.n, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    learner = Learner(cfg, net, seed=seed)
    policy = ActorPolicy(net, learner.train_state.params, collect_eps,
                         seed=seed)
    lb = LocalBuffer(learner.spec, policy.action_dim, cfg.optim.gamma,
                     cfg.optim.priority_eta)

    obs = env.reset()
    policy.observe_reset(obs)
    lb.reset(obs)

    def collect_one():
        nonlocal obs
        action, q, hidden = policy.act()
        next_obs, reward, done, _ = env.step(action)
        policy.observe(next_obs, action)
        lb.add(action, reward, next_obs, q, hidden)
        if done:
            learner.ingest(lb.finish(None))
            obs = env.reset()
            policy.observe_reset(obs)
            lb.reset(obs)
        elif len(lb) == learner.spec.block_length:
            learner.ingest(lb.finish(policy.bootstrap_q()))

    def check_deadline():
        if deadline is not None and time.time() > deadline:
            raise TimeoutError(
                f"sync_train exceeded its wall-clock bound at "
                f"{learner.training_steps}/{train_steps} steps")

    try:
        while not learner.ready:
            collect_one()
            check_deadline()
        while learner.training_steps < train_steps:
            for _ in range(ratio):      # exact collect:learn ratio
                collect_one()
            learner.step()
            if learner.training_steps % param_refresh_interval == 0:
                policy.update_params(learner.train_state.params)
            check_deadline()
    finally:
        env.close()    # every exit path — failing genomes must not leak fds
    return net, learner


def greedy_return(net, params, env_cfg, seed: int,
                  max_steps: int = 100_000) -> float:
    """One greedy (ε=0) episode's summed reward; deterministic given seed."""
    from r2d2_tpu.actor.policy import ActorPolicy
    from r2d2_tpu.envs.factory import create_env
    env = create_env(env_cfg, seed=seed)
    policy = ActorPolicy(net, params, epsilon=0.0, seed=seed)
    obs = env.reset()
    policy.observe_reset(obs)
    total, done, steps = 0.0, False, 0
    while not done and steps < max_steps:
        action, _, _ = policy.act()
        obs, reward, done, _ = env.step(action)
        policy.observe(obs, action)
        total += reward
        steps += 1
    env.close()
    return total


def sync_fitness(cfg: Config, train_steps: int,
                 eval_seeds: Sequence[int] = (123, 456),
                 collect_eps: float = 0.4, seed: int = 0,
                 max_seconds: float = None) -> float:
    """Deterministic fitness: sync-train then mean greedy return over
    ``eval_seeds``. The same ``(cfg, seeds)`` scores bit-identically.
    ``max_seconds`` bounds the whole evaluation (TimeoutError past it)."""
    import time

    import numpy as np
    deadline = time.time() + max_seconds if max_seconds else None
    net, learner = sync_train(cfg, train_steps, collect_eps, seed=seed,
                              deadline=deadline)
    returns = []
    for s in eval_seeds:
        if deadline is not None and time.time() > deadline:
            raise TimeoutError("sync_fitness exceeded its wall-clock bound "
                               "during greedy evaluation")
        returns.append(
            greedy_return(net, learner.train_state.params, cfg.env, s))
    return float(np.mean(returns))
