"""On-chip pallas kernel gate: compile + parity-check every kernel on the
REAL Mosaic pipeline in one command.

    python -m r2d2_tpu.cli.chip_checks            # all kernels
    python -m r2d2_tpu.cli.chip_checks --only lstm

Interpret-mode tests (the CPU suite) pin each kernel's semantics but
cannot catch Mosaic lowering rejections — historically the dominant
failure class (uint8->f32 cast, non-tile-aligned HBM slices, bf16
minor-dim insertion, strided-store width: all discovered only on chip).
This gate runs each kernel at a small but TILE-FAITHFUL shape (every
constraint the production shape exercises — uint8 (32,128) storage
tiles, 84x84 true frames under padded storage, bf16 compute — is
preserved) and checks bit/tolerance parity against the jnp twin, so a
lowering regression surfaces in minutes instead of mid-bench.

Exit code: 0 = all pass, 1 = any FAIL (error text printed per kernel).
"""

import sys
import time


def _check(name, fn):
    t0 = time.time()
    try:
        fn()
    except Exception as e:  # noqa: BLE001 — report and continue
        msg = str(e).splitlines()[0][:300] if str(e) else type(e).__name__
        print(f"FAIL {name} ({time.time()-t0:.1f}s): {type(e).__name__}: "
              f"{msg}")
        return False
    print(f"PASS {name} ({time.time()-t0:.1f}s)")
    return True


def run_chip_checks(only: str = "") -> int:
    # route JAX_PLATFORMS through jax.config BEFORE backend discovery —
    # the env var alone filters only after the (possibly wedged) axon
    # plugin initializes, so a JAX_PLATFORMS=cpu invocation would still
    # hang on a wedged tunnel (the exact failure this gate diagnoses)
    from r2d2_tpu.utils import pin_platform
    pin_platform()

    import numpy as np

    import jax
    import jax.numpy as jnp

    devs = jax.devices()
    print(f"backend: {devs[0].platform} ({devs[0].device_kind})")
    if devs[0].platform == "cpu":
        print("chip_checks needs an accelerator backend (pallas kernels "
              "do not lower on CPU); the CPU suite's interpret-mode tests "
              "cover semantics", file=sys.stderr)
        return 2

    rng = np.random.default_rng(0)
    checks = []

    def add(name, fn):
        if only in name:
            checks.append((name, fn))

    # --- obs decode (stack_frames), standard + padded-storage strip ------
    def decode():
        from r2d2_tpu.ops.pallas_kernels import (stack_frames_pallas,
                                                 stack_frames_reference)
        obs = jnp.asarray(rng.integers(0, 255, (4, 60, 84, 84)), jnp.uint8)
        for dtype in (jnp.float32, jnp.bfloat16):
            got = stack_frames_pallas(obs, 55, 4, out_dtype=dtype)
            want = stack_frames_reference(obs, 55, 4, out_dtype=dtype)
            np.testing.assert_array_equal(np.asarray(got, np.float32),
                                          np.asarray(want, np.float32))
    add("decode", decode)

    def decode_padded():
        from r2d2_tpu.ops.pallas_kernels import (stack_frames_pallas,
                                                 stack_frames_reference)
        obs = jnp.asarray(rng.integers(0, 255, (2, 60, 96, 128)), jnp.uint8)
        got = stack_frames_pallas(obs, 55, 4, out_dtype=jnp.bfloat16,
                                  out_height=84, out_width=84)
        want = stack_frames_reference(obs, 55, 4, out_dtype=jnp.bfloat16,
                                      out_height=84, out_width=84)
        np.testing.assert_array_equal(np.asarray(got, np.float32),
                                      np.asarray(want, np.float32))
    add("decode_padded_strip", decode_padded)

    # --- replay window gathers ------------------------------------------
    def row_gather():
        from r2d2_tpu.ops.pallas_kernels import (gather_rows_pallas,
                                                 gather_rows_reference)
        ring = jnp.asarray(rng.integers(0, 255, (8, 60, 84, 84)), jnp.uint8)
        bi = jnp.asarray(rng.integers(0, 8, (16,)), jnp.int32)
        st = jnp.asarray(rng.integers(0, 5, (16,)), jnp.int32)
        got = gather_rows_pallas(ring, bi, st, 55)
        want = gather_rows_reference(ring, bi, st, 55)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    add("row_gather", row_gather)

    def exact_gather():
        from r2d2_tpu.ops.pallas_kernels import (gather_rows_exact_pallas,
                                                 gather_rows_reference)
        # padded-storage tile shape (96, 128): the Mosaic alignment this
        # kernel exists for
        ring = jnp.asarray(rng.integers(0, 255, (8, 60, 96, 128)), jnp.uint8)
        bi = jnp.asarray(rng.integers(0, 8, (16,)), jnp.int32)
        st = jnp.asarray(rng.integers(0, 5, (16,)), jnp.int32)
        got = gather_rows_exact_pallas(ring, bi, st, 55)
        want = gather_rows_reference(ring, bi, st, 55)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    add("exact_gather", exact_gather)

    # --- fused LSTM scan: lean fwd, residual fwd, and the bwd kernel -----
    def lstm():
        from r2d2_tpu.ops.pallas_lstm import (lstm_scan_pallas,
                                              lstm_scan_reference)
        T, B, H = 55, 16, 512
        for dtype, tol in ((jnp.float32, 0.0), (jnp.bfloat16, 0.05)):
            for bt in (1, 5):        # the bench-swept block_t values
                xpb = jnp.asarray(rng.standard_normal((T, B, 4 * H)), dtype)
                wh = jnp.asarray(rng.standard_normal((H, 4 * H)) * 0.05,
                                 dtype)
                c0 = jnp.asarray(rng.standard_normal((B, H)), dtype)
                h0 = jnp.asarray(rng.standard_normal((B, H)), dtype)
                hs_p, (cf_p, hf_p) = lstm_scan_pallas(xpb, wh, c0, h0,
                                                      block_t=bt)
                hs_r, (cf_r, hf_r) = lstm_scan_reference(xpb, wh, c0, h0)
                np.testing.assert_allclose(
                    np.asarray(hs_p, np.float32),
                    np.asarray(hs_r, np.float32), atol=tol, rtol=tol)

                def loss(fn, a):
                    hs, (c, h) = fn(*a)
                    return (jnp.sum(hs.astype(jnp.float32) ** 2)
                            + jnp.sum(c.astype(jnp.float32))
                            + jnp.sum(h.astype(jnp.float32)))

                g_p = jax.grad(lambda a: loss(
                    lambda *x: lstm_scan_pallas(*x, block_t=bt), a))(
                        (xpb, wh, c0, h0))
                g_r = jax.grad(lambda a: loss(lstm_scan_reference, a))(
                    (xpb, wh, c0, h0))
                for name, a, b in zip(("dxpb", "dwh", "dc0", "dh0"),
                                      g_p, g_r):
                    a = np.asarray(a, np.float32)
                    b = np.asarray(b, np.float32)
                    assert np.isfinite(a).all(), \
                        f"{name} not finite (block_t={bt})"
                    denom = max(np.abs(b).max(), 1e-3)
                    gap = np.abs(a - b).max() / denom
                    gtol = 1e-4 if dtype == jnp.float32 else 0.25
                    assert gap < gtol, \
                        f"{name} rel gap {gap:.4f} > {gtol} (block_t={bt})"
    add("lstm_scan", lstm)

    # --- quantized acting forward (ISSUE 14): compile + parity ----------
    def quant_forward():
        # The int8 forward has no pallas kernel, but it is the first
        # program that streams int8 weights + per-channel scales through
        # the bf16 MXU matmul path — the compile itself (int8 dequant
        # fusion, mixed f32 LSTM carry under bf16 torso/head) is what
        # this cell validates on the real toolchain, plus tolerance
        # parity and greedy agreement against the f32 twin.
        import dataclasses

        from r2d2_tpu.actor.policy import make_forward_fn
        from r2d2_tpu.config import NetworkConfig
        from r2d2_tpu.models.network import (NetworkApply,
                                             make_inference_bundle)
        ncfg = dataclasses.replace(NetworkConfig(), inference_dtype="int8",
                                   space_to_depth="off")
        net = NetworkApply(6, ncfg, 4, 84, 84)
        params = net.init(jax.random.PRNGKey(0))
        bundle = jax.device_get(make_inference_bundle(net, params, 1))
        obs = rng.random((16, 84, 84, 4)).astype(np.float32)
        la = rng.integers(0, 6, 16).astype(np.int32)
        hid = rng.standard_normal((16, 2, 512)).astype(np.float32) * 0.1
        qfwd = make_forward_fn(net, probe_interval=1)
        a_q, q_q, h_q, probe = qfwd(bundle, obs, la, hid, np.int32(0),
                                    np.int32(16))
        f32fwd = make_forward_fn(net, "f32")
        a_f, q_f, h_f = f32fwd(params, obs, la, hid)
        dq, agree, probed = (float(np.asarray(x)) for x in probe)
        assert probed == 1.0, "probe branch did not fire at tick 0"
        scale = max(float(np.abs(np.asarray(q_f)).max()), 1e-3)
        assert float(np.abs(np.asarray(q_q) - np.asarray(q_f)).max()) \
            / scale < 0.05, "quantized Q diverges > 5% of Q range"
        host_agree = float(np.mean(np.asarray(a_q) == np.asarray(a_f)))
        assert agree >= 0.9 and host_agree >= 0.9, \
            f"greedy agreement {agree:.3f}/{host_agree:.3f} < 0.9"
        # the recurrent carry must come back f32 (drift containment)
        assert np.asarray(h_q).dtype == np.float32
    add("quant_forward", quant_forward)

    if not checks:
        print(f"no checks match --only={only!r}", file=sys.stderr)
        return 2
    ok = all([_check(name, fn) for name, fn in checks])
    return 0 if ok else 1


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    only = ""
    i = 0
    while i < len(argv):
        a = argv[i]
        if a.startswith("--only="):
            only = a.split("=", 1)[1]
        elif a == "--only" and i + 1 < len(argv):
            i += 1
            only = argv[i]
        elif a in ("-h", "--help"):
            print(__doc__)
            return 0
        else:
            print(f"unknown arg {a!r} (supported: --only SUBSTR)",
                  file=sys.stderr)
            return 2
        i += 1
    return run_chip_checks(only)


if __name__ == "__main__":
    sys.exit(main())
