"""Roofline report generator (ISSUE 9): analytic component costs + XLA
program costs + per-backend peak specs + measured step time, joined into
the PERF.md-style table — replacing the round-5 hand math.

What one run produces (JSON artifact + printed table):

  * per-component (torso / lstm / head / sum_tree / replay) FLOPs,
    bytes, arithmetic intensity, compute-vs-memory-bound classification
    against the backend's ridge point, and — when a step time is
    measured or given — %-of-peak per component;
  * the learner step's XLA totals from the fully-unrolled cost twin
    (telemetry/costmodel.py ``unroll_scans=True`` — XLA counts a
    while-loop body once, so only the unrolled program's FLOPs reflect
    executed work) with the parity check against
    ``bench.model_flops_per_step`` (the 5% acceptance bar);
  * the serial-chain critical-path model (iterations, FLOP share, the
    implied per-iteration latency at the measured step time);
  * the anakin acting program's totals + per-env-step compute.

Peaks come from telemetry/costmodel.PEAK_SPECS (v5e/v5p/v4/v6 bf16+f32
FLOP/s and HBM GB/s); the CPU backend gets a flagged NOMINAL fallback so
the report renders on the test backend without pretending to know the
host (override with --peak-flops / --hbm-gbps). Optionally join a
traceparse attribution summary (--trace-summary) to show measured
device-time shares next to the analytic ones.

    python -m r2d2_tpu.tools.roofline                       # auto preset
    python -m r2d2_tpu.tools.roofline --preset reference --out ROOFLINE.json
    make roofline
"""

import json
import sys
import time
from typing import Any, Dict, Optional

from r2d2_tpu.telemetry.costmodel import (analytic_component_costs,
                                          collect_cost_table, gate_config,
                                          model_flops_per_step, peak_spec)

ROOFLINE_VARIANTS = ("learner_step", "anakin_act", "replay_add_many",
                     "replay_sample")


def _preset_config(preset: str):
    from r2d2_tpu.config import Config
    if preset == "auto":
        import jax
        preset = "reference" if jax.default_backend() == "tpu" else "gate"
    if preset == "reference":
        # the real training shape; compiles take minutes on CPU — the
        # default there is the pinned gate fixture instead
        return Config().replace(**{"env.game_name": "Fake",
                                   "env.episode_len": 400}), "reference"
    if preset == "gate":
        return gate_config(), "gate"
    raise SystemExit(f"unknown preset {preset!r} (auto|gate|reference)")


def measure_step_time_ms(cfg, n_timed: int = 5) -> float:
    """Compile + time the production learner step on synthetic replay
    (the profile_step fill pattern) — median of ``n_timed`` dispatches."""
    import jax
    import numpy as np

    from r2d2_tpu.envs.factory import create_jax_env
    from r2d2_tpu.learner.train_step import (create_train_state,
                                             make_learner_step)
    from r2d2_tpu.models.network import NetworkApply
    from r2d2_tpu.replay.device_replay import replay_add, replay_init
    from r2d2_tpu.replay.structs import ReplaySpec
    from r2d2_tpu.replay.synthetic import make_synthetic_block

    spec = ReplaySpec.from_config(cfg)
    action_dim = create_jax_env(cfg.env).action_dim
    net = NetworkApply(action_dim, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    ts = create_train_state(jax.random.PRNGKey(1), net, cfg.optim)
    rs = replay_init(spec)
    rng = np.random.default_rng(0)
    for _ in range(min(spec.num_blocks, 8)):
        rs = replay_add(spec, rs, make_synthetic_block(spec, rng))
    step = make_learner_step(net, spec, cfg.optim, cfg.network.use_double)
    for _ in range(2):                                 # compile + warm
        ts, rs, m = step(ts, rs)
    jax.block_until_ready(m["loss"])
    times = []
    for _ in range(n_timed):
        t0 = time.perf_counter()
        ts, rs, m = step(ts, rs)
        jax.block_until_ready(m["loss"])
        times.append(time.perf_counter() - t0)
    return 1e3 * float(np.median(times))


def build_report(cfg, preset: str, step_time_ms: Optional[float],
                 peak: Dict[str, Any],
                 trace_summary: Optional[dict] = None) -> Dict[str, Any]:
    """The joined roofline report — pure given its inputs (the CLI
    measures/loads them), so tests can golden-file the analytic side."""
    from r2d2_tpu.envs.factory import create_jax_env
    action_dim = create_jax_env(cfg.env).action_dim
    xla = collect_cost_table(cfg, variants=ROOFLINE_VARIANTS,
                             unroll_scans=True)
    programs = xla["programs"]

    # the RESOLVED compute dtype picks both the peak FLOP/s row and the
    # analytic activation byte size — judging bf16 flops against a bf16
    # peak while counting f32 activation bytes would understate every
    # component's arithmetic intensity 2x on TPU
    from r2d2_tpu.models.network import NetworkApply
    net = NetworkApply(action_dim, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    bf16 = bool(net.config.bf16)
    analytic = analytic_component_costs(cfg, action_dim,
                                        act_bytes=2 if bf16 else 4)
    peak_flops = float(peak["flops_bf16" if bf16 else "flops_f32"])
    bw_bytes = float(peak["hbm_gbps"]) * 1e9
    ridge = peak_flops / bw_bytes            # FLOPs/byte at the roofline knee

    step_s = step_time_ms / 1e3 if step_time_ms else None
    comp_rows: Dict[str, Any] = {}
    total_flops = analytic["total_flops"]
    trace_comps = (trace_summary or {}).get("components") or {}
    for name, c in analytic["components"].items():
        ai = c["flops"] / c["bytes"] if c["bytes"] else 0.0
        row = {
            "flops": c["flops"],
            "bytes": c["bytes"],
            "arithmetic_intensity": round(ai, 4),
            "bound": "compute" if ai >= ridge else "memory",
            "share_of_flops": round(c["flops"] / total_flops, 6)
            if total_flops else 0.0,
            # the component's floor at peak: whichever wall it hits
            "time_at_peak_ms": round(1e3 * max(
                c["flops"] / peak_flops, c["bytes"] / bw_bytes), 6),
        }
        if step_s:
            row["pct_of_peak"] = round(
                100.0 * c["flops"] / (step_s * peak_flops), 4)
        if name in trace_comps:
            row["device_time_share"] = trace_comps[name].get("share")
        comp_rows[name] = row

    lstep = programs.get("learner_step", {})
    xla_flops = lstep.get("flops")
    mfps = analytic["model_flops_per_step"]
    parity = {
        "xla_flops": xla_flops,
        "model_flops_per_step": mfps,
        "ratio": (round(xla_flops / mfps, 4)
                  if xla_flops and mfps else None),
    }

    serial = dict(analytic["serial_chain"])
    serial["floor_at_peak_ms"] = round(
        1e3 * serial["flops"] / peak_flops, 6)
    if step_s:
        # upper bound on the chain's per-iteration latency: the whole
        # measured step attributed to the chain (reality overlaps — the
        # PERF.md round-5 additive model brackets it from both sides)
        serial["implied_tau_us_upper"] = round(
            1e6 * step_s / serial["iterations"], 3)

    report = {
        "schema": 1,
        "preset": preset,
        "backend": xla["backend"],
        "peak": peak,
        "compute_dtype": "bf16" if bf16 else "f32",
        "ridge_flops_per_byte": round(ridge, 4),
        "shape": xla["shape"],
        "action_dim": action_dim,
        "learner_step": {
            "measured_ms": step_time_ms,
            "xla": lstep,
            "total_flops_analytic": total_flops,
            "pct_of_peak_total": (round(
                100.0 * total_flops / (step_s * peak_flops), 4)
                if step_s else None),
            "components": comp_rows,
            "serial_chain": serial,
        },
        "parity": parity,
        "anakin_act": None,
        "programs": programs,
    }
    act = programs.get("anakin_act")
    if act:
        seg_steps = cfg.actor.anakin_lanes * cfg.replay.block_length
        report["anakin_act"] = {
            "xla": act,
            "env_steps_per_segment": seg_steps,
            "flops_per_env_step": (round(act["flops"] / seg_steps, 1)
                                   if act.get("flops") else None),
        }
    if trace_summary is not None:
        report["trace_attribution"] = {
            "attributed_frac": trace_summary.get("attributed_frac"),
            "total_us": trace_summary.get("total_us"),
        }
    return report


def format_report(report: Dict[str, Any]) -> str:
    ls = report["learner_step"]
    peak = report["peak"]
    lines = []
    nominal = " [NOMINAL peaks — CPU fallback, do not quote]" \
        if peak.get("nominal") else ""
    lines.append(
        f"roofline @ {peak.get('device_kind')} "
        f"({report['compute_dtype']} peak "
        f"{peak['flops_bf16' if report['compute_dtype'] == 'bf16' else 'flops_f32'] / 1e12:.1f} "
        f"TFLOP/s, {peak['hbm_gbps']:.0f} GB/s, ridge "
        f"{report['ridge_flops_per_byte']:.1f} FLOP/B){nominal}")
    mm = ls["measured_ms"]
    lines.append(
        f"learner step: {ls['total_flops_analytic'] / 1e9:.3f} GFLOP "
        + (f"measured {mm:.3f} ms -> {ls['pct_of_peak_total']:.2f}% of peak"
           if mm else "(no measured step time)"))
    lines.append(f"{'component':<10}{'GFLOP':>10}{'MB':>10}{'AI':>9}"
                 f"{'bound':>9}{'%flops':>8}{'%peak':>8}")
    for name, r in ls["components"].items():
        pct = r.get("pct_of_peak")
        lines.append(
            f"{name:<10}{r['flops'] / 1e9:>10.4f}{r['bytes'] / 2**20:>10.2f}"
            f"{r['arithmetic_intensity']:>9.1f}{r['bound']:>9}"
            f"{100 * r['share_of_flops']:>7.1f}%"
            + (f"{pct:>7.2f}%" if pct is not None else f"{'-':>8}"))
    sc = ls["serial_chain"]
    lines.append(
        f"serial chain: {sc['iterations']} dependent iterations, "
        f"{100 * sc['share_of_total']:.1f}% of FLOPs, floor at peak "
        f"{sc['floor_at_peak_ms']:.4f} ms"
        + (f", implied tau <= {sc['implied_tau_us_upper']:.1f} us/iter"
           if "implied_tau_us_upper" in sc else ""))
    par = report["parity"]
    if par["ratio"] is not None:
        lines.append(
            f"parity: XLA unrolled {par['xla_flops'] / 1e9:.3f} GFLOP vs "
            f"model_flops_per_step {par['model_flops_per_step'] / 1e9:.3f} "
            f"GFLOP (ratio {par['ratio']:.4f})")
    act = report.get("anakin_act")
    if act:
        fpes = act["flops_per_env_step"]
        lines.append(
            f"anakin act: {act['xla'].get('flops', 0) / 1e9:.4f} GFLOP / "
            f"segment = "
            + (f"{fpes:.0f}" if fpes is not None else "-")
            + f" FLOP/env-step ({act['env_steps_per_segment']} "
              "steps/segment)")
    ta = report.get("trace_attribution")
    if ta:
        lines.append(f"trace attribution: "
                     f"{100 * (ta.get('attributed_frac') or 0):.1f}% of "
                     f"{(ta.get('total_us') or 0) / 1e3:.2f} ms device time "
                     "mapped to components")
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--preset", default="auto",
                   help="auto (gate on CPU, reference on TPU) | gate | "
                        "reference")
    p.add_argument("--out", default="ROOFLINE.json")
    p.add_argument("--step-time-ms", type=float, default=None,
                   help="use this step time instead of measuring")
    p.add_argument("--no-measure", action="store_true",
                   help="skip the live step timing (%%-of-peak omitted)")
    p.add_argument("--peak-flops", type=float, default=None,
                   help="override the peak FLOP/s (both dtypes)")
    p.add_argument("--hbm-gbps", type=float, default=None,
                   help="override the memory bandwidth (GB/s)")
    p.add_argument("--trace-summary", default="",
                   help="traceparse attribution JSON to join "
                        "(per-component measured device-time shares)")
    args = p.parse_args(argv)

    cfg, preset = _preset_config(args.preset)
    peak = peak_spec()
    if args.peak_flops:
        peak = dict(peak, flops_bf16=args.peak_flops,
                    flops_f32=args.peak_flops, nominal=False)
    if args.hbm_gbps:
        peak = dict(peak, hbm_gbps=args.hbm_gbps)

    step_ms = args.step_time_ms
    if step_ms is None and not args.no_measure:
        print("measuring learner step time ...", file=sys.stderr)
        step_ms = measure_step_time_ms(cfg)

    trace_summary = None
    if args.trace_summary:
        with open(args.trace_summary) as f:
            trace_summary = json.load(f)

    report = build_report(cfg, preset, step_ms, peak,
                          trace_summary=trace_summary)
    print(format_report(report))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
