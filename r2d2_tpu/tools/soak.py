"""Production-scale soak: sustained training at reference scale on one chip.

The e2e test suite runs at toy shapes (24x24 frames, capacity 800); this
drives the DEFAULT configuration — capacity 500k env steps at 84x84x4,
exact-gather padded storage, bf16 + pallas + spd16 on TPU — through a
sustained window (default 30 min) and reports what a production deployment
would hit (VERDICT r4 #3):

  * replay_init at full capacity (the HBM guard refuses with numbers
    instead of OOMing if the ring cannot fit);
  * a FULL ring fill + wrap before training (ring-lap correctness at
    scale), then continuous ingestion at the reference's collect:learn
    ratio so the ring keeps wrapping during training;
  * steps/s sampled per minute — steady-state drift after the wrap is the
    headline ("post-wrap slowdown" would indicate fragmentation/layout
    trouble);
  * device memory stats at init / after fill / end (peak bytes in use);
  * checkpoint cadence: full orbax saves on a wall-clock interval,
    timed.

Ingestion uses a device-resident synthetic block re-added with varying
priorities (one host->device transfer total): the soak measures the
DEVICE side — ring behavior, HBM, steady-state step time — not actor
throughput, which the orchestrator/chaos tests cover.

Reference analog: the reference trains multi-day runs at this capacity
(/root/reference/config.py, /root/reference/worker.py:40-43); it publishes
no soak artifact. Output: one JSON line, machine-readable.
"""

import dataclasses
import json
import os
import sys
import time

import numpy as np


def _mem_stats():
    # the ONE memory_stats wrapper (telemetry/resources.py), same
    # backend-optional fallback this helper always had: {} on CPU or when
    # the call raises, the summary byte counters otherwise
    from r2d2_tpu.telemetry.resources import (SUMMARY_KEYS,
                                              device_memory_stats)
    return device_memory_stats(keys=SUMMARY_KEYS)


def run_soak(duration_s: float = 1800.0, capacity: int = 500_000,
             checkpoint_interval_s: float = 300.0,
             save_dir: str = "/tmp/r2d2_soak",
             config_overrides: dict = None) -> dict:
    from r2d2_tpu.utils import pin_platform
    pin_platform()
    import jax

    from r2d2_tpu.config import Config
    from r2d2_tpu.learner import create_train_state
    from r2d2_tpu.learner.train_step import (make_learner_step,
                                             make_multi_learner_step)
    from r2d2_tpu.models import NetworkApply
    from r2d2_tpu.replay import ReplaySpec, replay_add, replay_init
    from r2d2_tpu.replay.device_replay import replay_size
    from r2d2_tpu.replay.synthetic import make_synthetic_block
    from r2d2_tpu.runtime.checkpoint import save_checkpoint

    overrides = {"replay.capacity": capacity, "runtime.save_dir": save_dir}
    overrides.update(config_overrides or {})
    cfg = Config().replace(**overrides)
    spec = ReplaySpec.from_config(cfg)
    action_dim = 18                         # full Atari action set
    dev = jax.devices()[0]
    out = {"metric": "soak", "device_kind": dev.device_kind,
           "platform": dev.platform, "capacity": capacity,
           "num_blocks": spec.num_blocks,
           "exact_gather": bool(spec.exact_gather),
           "ring_gib": round(spec.device_ring_bytes / 2**30, 2),
           "duration_target_s": duration_s}
    print(f"soak: {dev.platform} ({dev.device_kind}), ring "
          f"{out['ring_gib']} GiB over {spec.num_blocks} blocks, "
          f"exact_gather={spec.exact_gather}", file=sys.stderr)

    # --- init (the HBM guard fires here on an oversized ring) -----------
    t0 = time.time()
    rs = replay_init(spec)
    jax.block_until_ready(rs.tree)
    out["init_s"] = round(time.time() - t0, 1)
    out["mem_after_init"] = _mem_stats()

    # --- one full ring lap BEFORE training ------------------------------
    # one host block, device-committed once; re-adds vary only priorities
    # (jitted in replay_add) so the fill is dispatch-bound, not
    # tunnel-transfer-bound
    rng = np.random.default_rng(0)
    block = jax.device_put(make_synthetic_block(spec, rng))
    t0 = time.time()
    wrap_extra = max(2, spec.num_blocks // 50)
    for i in range(spec.num_blocks + wrap_extra):
        rs = replay_add(spec, rs, block)
        if i % 200 == 0:            # bound the in-flight dispatch queue
            jax.block_until_ready(rs.tree)
    jax.block_until_ready(rs.tree)
    out["fill_s"] = round(time.time() - t0, 1)
    out["ring_laps_fill"] = round(
        (spec.num_blocks + wrap_extra) / spec.num_blocks, 3)
    # OBSERVED wrap evidence (not derived from the loop bounds): a full
    # buffer and a pointer that came back around the ring
    out["buffer_steps_after_fill"] = int(replay_size(rs))
    out["block_ptr_after_fill"] = int(rs.block_ptr)
    out["mem_after_fill"] = _mem_stats()
    print(f"soak: ring filled+wrapped in {out['fill_s']}s "
          f"(buffer={out['buffer_steps_after_fill']} steps, "
          f"ptr={out['block_ptr_after_fill']})", file=sys.stderr)

    # --- steady-state training with interleaved ingestion ---------------
    net = NetworkApply(action_dim, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    ts = create_train_state(jax.random.PRNGKey(0), net, cfg.optim)
    spd = cfg.runtime.resolved_steps_per_dispatch()
    if spd > 1:
        step = make_multi_learner_step(net, spec, cfg.optim,
                                       cfg.network.use_double, spd)
    else:
        step = make_learner_step(net, spec, cfg.optim, cfg.network.use_double)

    t0 = time.time()
    ts, rs, m = step(ts, rs)
    jax.block_until_ready(m["loss"])
    out["compile_s"] = round(time.time() - t0, 1)

    # ingestion cadence at the reference collect:learn shape: one block
    # (block_length env steps) per block_length/ratio train steps
    ratio = max(float(cfg.replay.max_env_steps_per_train_step), 1.0)
    dispatches_per_add = max(1, int(round(
        cfg.replay.block_length / ratio / spd)))

    start = time.time()
    deadline = start + duration_s
    next_minute = start + 60.0
    next_ckpt = start + checkpoint_interval_s
    timeline = []                 # per-minute steps/s
    ckpt_times = []
    adds = dispatches = 0
    window_dispatches = 0
    window_t0 = start
    losses = []
    while time.time() < deadline:
        ts, rs, m = step(ts, rs)
        dispatches += 1
        window_dispatches += 1
        if dispatches % dispatches_per_add == 0:
            rs = replay_add(spec, rs, block)
            adds += 1
        if dispatches % 25 == 0:  # bound the dispatch queue + sample loss
            jax.block_until_ready(m["loss"])
            losses.append(float(np.asarray(m["loss"]).reshape(-1)[-1]))
        now = time.time()
        if now >= next_minute:
            jax.block_until_ready(m["loss"])
            now = time.time()
            timeline.append(round(
                window_dispatches * spd / (now - window_t0), 1))
            window_t0, window_dispatches = now, 0
            next_minute += 60.0
            print(f"soak: minute {len(timeline)}: "
                  f"{timeline[-1]} steps/s", file=sys.stderr)
        if now >= next_ckpt:
            tck = time.time()
            save_checkpoint(save_dir, cfg.env.game_name,
                            len(ckpt_times) + 1, 0, ts.params, ts.opt_state,
                            ts.target_params, int(ts.step),
                            adds * cfg.replay.block_length,
                            config_json=cfg.to_json())
            ckpt_times.append(round(time.time() - tck, 1))
            next_ckpt += checkpoint_interval_s
    jax.block_until_ready(m["loss"])
    total = time.time() - start

    out["train_s"] = round(total, 1)
    out["train_steps"] = dispatches * spd
    out["steps_per_sec_mean"] = round(dispatches * spd / total, 1)
    out["steps_per_sec_timeline"] = timeline
    out["ring_laps_train"] = round(adds / spec.num_blocks, 3)
    out["checkpoint_save_s"] = ckpt_times
    out["losses_sampled"] = [round(x, 4) for x in losses[-5:]]
    out["mem_end"] = _mem_stats()
    if len(timeline) >= 4:
        first = np.mean(timeline[:2])
        last = np.mean(timeline[-2:])
        out["steady_state_drift_pct"] = round(100 * (last - first) / first, 2)
    return out


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seconds", type=float,
                   default=float(os.environ.get("R2D2_SOAK_SECONDS", 1800)))
    p.add_argument("--capacity", type=int, default=500_000)
    p.add_argument("--checkpoint-interval", type=float, default=300.0)
    p.add_argument("--save-dir", default="/tmp/r2d2_soak")
    p.add_argument("--override", action="append", default=[],
                   help="dotted config override key=value (repeatable)")
    def _env_float(name, fallback):
        try:
            return float(os.environ.get(name) or fallback)
        except ValueError:
            return fallback

    p.add_argument("--e2e-seconds", type=float,
                   default=_env_float("R2D2_SOAK_E2E_SECONDS", 0.0),
                   help="also run the end-to-end actors→learner throughput "
                        "phase (tools/e2e_bench.py: process-mode vector "
                        "actors feeding the real learner; reports "
                        "env-steps/s and learner steps/s together); 0 = off")
    p.add_argument("--e2e-envs-per-actor", type=int, default=16)
    p.add_argument("--chaos-seconds", type=float,
                   default=_env_float("R2D2_SOAK_CHAOS_SECONDS", 0.0),
                   help="also run the chaos phase (tools/chaos.py): train "
                        "with injected crash-loop + hang faults and report "
                        "what supervision did (restarts, hang detections, "
                        "breaker trips) alongside proof training kept "
                        "advancing; 0 = off")
    p.add_argument("--chaos-actor-mode", choices=("thread", "process"),
                   default="process")
    args = p.parse_args(argv)
    overrides = {}
    for ov in args.override:
        k, _, v = ov.partition("=")
        try:                       # JSON value where it parses (numbers,
            overrides[k] = json.loads(v)   # lists, booleans) ...
        except (json.JSONDecodeError, ValueError):
            overrides[k] = v       # ... plain string otherwise ("tennis")
    out = run_soak(args.seconds, args.capacity, args.checkpoint_interval,
                   args.save_dir, overrides)
    if args.e2e_seconds > 0:
        # system-level phase AFTER the device soak: the chip is released by
        # then, and a failure here must not lose the soak numbers
        from r2d2_tpu.tools.e2e_bench import run_e2e
        try:
            # same --override set as the soak phase (user overrides beat
            # run_e2e's CPU-reduced defaults), so an on-TPU soak can run
            # the e2e phase at the reference training shape
            out["e2e"] = run_e2e(args.e2e_seconds,
                                 envs_per_actor=args.e2e_envs_per_actor,
                                 overrides=overrides)
        except Exception as e:     # pragma: no cover - defensive
            out["e2e"] = {"error": repr(e)}
    if args.chaos_seconds > 0:
        # chaos phase LAST, same failure isolation as the e2e phase: a
        # wedged fault-injection run must not lose the soak numbers
        from r2d2_tpu.tools.chaos import run_chaos
        try:
            out["chaos"] = run_chaos(args.chaos_seconds,
                                     actor_mode=args.chaos_actor_mode)
        except Exception as e:     # pragma: no cover - defensive
            out["chaos"] = {"error": repr(e)}
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
