"""Fleet control tower CLI: the cross-plane dashboard + offline replay
over a run directory's per-process streams (ISSUE 19).

The per-plane tools each watch ONE stream — ``tools/inspect.py`` the
learner record, ``tools/sentinel.py`` whichever JSONL it is pointed at.
This tool watches the FLEET: it joins the newest row of every stream the
run directory carries (learner records, serving-fleet rows, standalone
ReplayService rows, multihost host rows, every alerts log) into one
joined record (:class:`~r2d2_tpu.telemetry.tower.TowerCollector`),
derives the cross-plane signals (end-to-end experience latency, the
shed-while-backlog correlation, spill promotion latency, plane
staleness) and runs the tower rule set over each join.

Modes, on the sentinel pattern:

  * **offline replay** (default): walk the full stream histories
    index-aligned (every plane logs on the same ``runtime.log_interval``
    cadence), evaluate every joined record, print the firings. Exit
    code 1 when any ``crit`` tower rule fired — a soak wrapper gates on
    it exactly like the per-stream sentinel.
  * **live watch** (``--follow``): redraw one dashboard frame per poll
    over the newest join, the ``tools/inspect.py --follow`` treatment
    widened to every plane.

Honors the ``telemetry.tower_enabled`` kill switch (exit 0, no reads,
when off — override per ``--override telemetry.tower_enabled=true``).
Firings can append to a JSONL via ``--out`` for the paper trail.

    python -m r2d2_tpu.tools.tower --dir models              # replay
    python -m r2d2_tpu.tools.tower --dir models --follow     # live
    python -m r2d2_tpu.tools.tower --rules                   # rule table
"""

import json
import sys
import time


def main(argv=None) -> int:
    import argparse

    from r2d2_tpu.config import Config
    from r2d2_tpu.telemetry.tower import (TowerCollector, render_tower,
                                          tower_rules)

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--dir", default="models",
                   help="the run's save_dir (all plane streams live "
                        "there)")
    p.add_argument("--follow", action="store_true",
                   help="live dashboard: redraw one joined frame per "
                        "poll instead of replaying the histories")
    p.add_argument("--interval", type=float, default=2.0,
                   help="poll cadence in follow mode")
    p.add_argument("--out", default="",
                   help="also append tower firings to this JSONL "
                        "(existing history is kept)")
    p.add_argument("--rules", action="store_true",
                   help="print the effective tower rule table and exit")
    p.add_argument("--override", action="append", default=[],
                   help="dotted config override key=value (repeatable), "
                        "e.g. telemetry.alerts_e2e_latency_growth=2")
    args = p.parse_args(argv)

    overrides = {}
    for ov in args.override:
        k, _, v = ov.partition("=")
        try:
            overrides[k] = json.loads(v)
        except (json.JSONDecodeError, ValueError):
            overrides[k] = v
    cfg = Config().replace(**overrides)

    if args.rules:
        print(f"{'rule':<32}{'kind':<11}{'severity':<9}{'bound':>10}  path")
        for r in tower_rules(cfg):
            print(f"{r.name:<32}{r.kind:<11}{r.severity:<9}"
                  f"{r.bound:>10}  {'.'.join(r.path)}")
        return 0

    if not (cfg.telemetry.enabled and cfg.telemetry.tower_enabled):
        print("tower disabled (telemetry.tower_enabled=false)")
        return 0

    collector = TowerCollector(args.dir, cfg,
                               jsonl_path=args.out or None)

    if not args.follow:
        records = collector.replay()
        if not records:
            print(f"no plane streams under {args.dir!r}", file=sys.stderr)
            return 2
        fired = crit = 0
        for i, rec in enumerate(records):
            for a in rec["alerts"]["fired"]:
                fired += 1
                if a.get("severity") == "crit":
                    crit += 1
                print(f"join#{i:>4} "
                      f"{a.get('severity', '?'):>4} {a['rule']}"
                      + (f" value={a['value']:.4g}"
                         if a.get("value") is not None else "")
                      + (f" baseline={a['baseline']:.4g}"
                         if a.get("baseline") is not None else ""))
        print(f"-- {len(records)} joined record(s), {fired} tower "
              f"alert(s) ({crit} crit)")
        print(render_tower(records[-1]))
        return 1 if crit else 0

    while True:
        record = collector.snapshot()
        frame = render_tower(record)
        if sys.stdout.isatty():
            sys.stdout.write("\x1b[2J\x1b[H")   # clear + home
        print(f"== control tower: {args.dir} "
              f"(t_wall={record['t_wall']:.0f}) ==")
        print(frame, flush=True)
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
