"""Noise-aware bench regression gate (ISSUE 7): compare fresh
``E2E_*``/``BENCH_*`` artifacts against the ``bench`` section of
``BASELINE.json``.

Every perf round leaves a JSON artifact (tools/e2e_bench.py A/Bs,
bench.py's learner matrix), but nothing ever COMPARED two rounds — a 20%
throughput regression would merge silently as long as tests stayed
green. This gate closes that hole:

  * ``--update`` snapshots the throughput metrics of every artifact in
    ``--dir`` into ``BASELINE.json["bench"]`` (one dotted-path → value
    map per artifact file);
  * the default run re-extracts the same metrics from the CURRENT
    artifacts and fails (exit 1) when any falls more than its tolerance
    below baseline.

Noise policy — the reason tolerances are per-metric, not one number:
single e2e cells swing ±10% run-to-run on a small shared host (2-core
scheduling noise; measured in rounds 8–11), which is why the A/B
harnesses run INTERLEAVED repeats and quote per-arm medians. The gate
mirrors that: ``*_ratio`` headlines (already medians of interleaved
arms) get the tight tolerance, raw ``*_per_sec`` cells (single runs) the
loose one — tight enough that the acceptance fixture (a synthetic 20%
throughput drop) always fails, loose enough that honest re-runs of the
same tree pass. Watched metrics are HIGHER-IS-BETTER by construction
(throughputs, speedups, on/off ratios); improvements never fail, they
just become the new floor at the next ``--update``.

Cost gate (ISSUE 9): alongside the wall-clock bench metrics, the
``costs`` section of BASELINE.json snapshots the XLA per-program cost
table (telemetry/costmodel.gate_table — flops / bytes accessed / buffer
sizes of every step factory at a pinned tiny config, CPU-pinned so the
numbers are backend-independent). Unlike the noise-tolerant bench gate,
the costs comparison is EXACT-match (analytic counts are deterministic):
a refactor that silently doubles a step's FLOPs or bytes fails ``make
regress`` even on wall-clock-noisy hosts, in BOTH directions. ``--update``
re-baselines it like the bench metrics; ``--skip-costs`` skips the
recompute (it costs ~20-30 s of tiny-config compiles).

    python -m r2d2_tpu.tools.regress                      # gate (make regress)
    python -m r2d2_tpu.tools.regress --update             # re-baseline
    python -m r2d2_tpu.tools.regress --artifacts E2E_r11.json
    python -m r2d2_tpu.tools.regress --skip-costs         # bench only
"""

import glob
import json
import os
import sys
from typing import Dict, List, Optional

# (suffix/substring match on the metric's KEY, tolerance as allowed
# relative drop). First match wins, top to bottom.
DEFAULT_TOLERANCES = (
    ("_ratio", 0.10),          # interleaved-repeat medians (A/B headlines)
    ("speedup", 0.15),         # derived from two single-run cells
    ("vs_baseline", 0.15),
    ("_per_sec", 0.15),        # raw single-run cells (±10% host noise)
    ("value", 0.15),           # bench.py headline
)
_WATCH = tuple(k for k, _ in DEFAULT_TOLERANCES)
DEFAULT_GLOBS = ("E2E_*.json", "BENCH_*.json")


def metric_tolerance(path: str, override: Optional[float] = None) -> float:
    if override is not None:
        return override
    key = path.rsplit(".", 1)[-1]
    for pat, tol in DEFAULT_TOLERANCES:
        if key == pat or key.endswith(pat) or pat in key:
            return tol
    return 0.15


def extract_metrics(obj, prefix: str = "") -> Dict[str, float]:
    """Flatten an artifact to {dotted.path: value} over the watched
    throughput keys. Lists are skipped (the ``*_cells`` arrays are the
    noise the medians exist to absorb), as is anything under a
    ``config`` block or a stale last-good re-emission (bench.py tags
    those ``stale: true`` — gating on a number the current tree never
    produced would misattribute an old regression to this change)."""
    out: Dict[str, float] = {}
    if not isinstance(obj, dict) or obj.get("stale") is True:
        return out
    for k, v in obj.items():
        path = f"{prefix}.{k}" if prefix else k
        if k == "config":
            continue
        if isinstance(v, dict):
            out.update(extract_metrics(v, path))
        elif isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        elif any(k == p or k.endswith(p) or p in k for p in _WATCH):
            out[path] = float(v)
    return out


def load_artifact(path: str) -> Optional[dict]:
    """The artifact's JSON object; artifacts are single-object files
    (possibly one JSON line). None when unreadable/unparseable."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def collect(run_dir: str, patterns=DEFAULT_GLOBS,
            names: Optional[List[str]] = None) -> Dict[str, dict]:
    """{artifact filename: metrics} for every readable artifact in
    ``run_dir`` matching the globs (or the explicit ``names``)."""
    if names:
        files = [os.path.join(run_dir, n) for n in names]
    else:
        files = sorted(p for pat in patterns
                       for p in glob.glob(os.path.join(run_dir, pat)))
    out = {}
    for path in files:
        doc = load_artifact(path)
        if doc is None:
            continue
        metrics = extract_metrics(doc)
        if metrics:
            out[os.path.basename(path)] = metrics
    return out


def compare(baseline: Dict[str, dict], current: Dict[str, dict],
            tolerance: Optional[float] = None) -> List[dict]:
    """One row per baselined metric: ok / REGRESSION / missing. New
    artifacts/metrics absent from the baseline are NOT rows — they join
    at the next ``--update``."""
    rows = []
    for fname, metrics in sorted(baseline.items()):
        cur = current.get(fname)
        for path, base in sorted(metrics.items()):
            tol = metric_tolerance(path, tolerance)
            row = {"artifact": fname, "metric": path, "baseline": base,
                   "tolerance": tol}
            if cur is None or path not in cur:
                # a vanished artifact/metric is a gate failure too: the
                # silent way to pass is to stop producing the number
                row.update({"current": None, "status": "missing"})
            else:
                value = cur[path]
                row["current"] = value
                if base > 0 and value < (1.0 - tol) * base:
                    row["status"] = "REGRESSION"
                    row["drop_pct"] = round(100.0 * (1.0 - value / base), 1)
                else:
                    row["status"] = "ok"
            rows.append(row)
    return rows


def _current_costs():
    # CPU-pinned with a >= 2-device virtual mesh (the sharded variant)
    # so the snapshot is identical on a TPU host and the test
    # container; a no-op when a wide-enough backend is already
    # initialized (the pin only binds before first backend init).
    # gate_table() itself memoizes per process; the attribute lookup
    # stays late-bound so tests can stub the recompute.
    from r2d2_tpu.telemetry import costmodel
    from r2d2_tpu.utils.platform import pin_cpu_platform
    pin_cpu_platform(2)
    return costmodel.gate_table()


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--baseline", default="BASELINE.json")
    p.add_argument("--dir", default=".",
                   help="directory holding the fresh artifacts")
    p.add_argument("--artifacts", nargs="*", default=None,
                   help="explicit artifact filenames (default: the "
                        "E2E_*/BENCH_* globs)")
    p.add_argument("--tolerance", type=float, default=None,
                   help="override the per-metric tolerance table with one "
                        "relative-drop bound for everything")
    p.add_argument("--update", action="store_true",
                   help="snapshot the current artifacts' metrics (and the "
                        "cost table) into the baseline and exit")
    p.add_argument("--skip-costs", action="store_true",
                   help="skip the XLA cost-table gate/update (saves the "
                        "~20-30 s of tiny-config compiles)")
    p.add_argument("--costs-rtol", type=float, default=1e-6,
                   help="relative tolerance of the exact-match costs gate")
    p.add_argument("--quiet", action="store_true",
                   help="only print regressions and the verdict")
    args = p.parse_args(argv)

    try:
        with open(args.baseline) as f:
            baseline_doc = json.load(f)
    except FileNotFoundError:
        print(f"no baseline at {args.baseline}", file=sys.stderr)
        return 2

    current = collect(args.dir, names=args.artifacts)

    current_costs = _current_costs

    if args.update:
        baseline_doc["bench"] = current
        n = sum(len(m) for m in current.values())
        msg = (f"baselined {n} metrics from {len(current)} artifact(s) "
               f"into {args.baseline}")
        if not args.skip_costs:
            baseline_doc["costs"] = current_costs()
            msg += (f" + {len(baseline_doc['costs']['programs'])} "
                    "cost-table program(s)")
        with open(args.baseline, "w") as f:
            json.dump(baseline_doc, f, indent=2)
            f.write("\n")
        print(msg)
        return 0

    bench = baseline_doc.get("bench")
    costs_gated = bool(baseline_doc.get("costs")) and not args.skip_costs
    if not bench and not costs_gated:
        # an EMPTY bench section is fine once the costs gate exists —
        # fail only when there is nothing at all to gate against
        print(f"{args.baseline} has no 'bench' section — run with "
              "--update first to snapshot the current artifacts",
              file=sys.stderr)
        return 2

    rows = compare(bench or {}, current, tolerance=args.tolerance)
    bad = [r for r in rows if r["status"] != "ok"]
    for r in rows:
        if args.quiet and r["status"] == "ok":
            continue
        cur = "-" if r["current"] is None else f"{r['current']:.10g}"
        extra = (f"  (-{r['drop_pct']}% > {r['tolerance']:.0%} tolerance)"
                 if r["status"] == "REGRESSION" else "")
        print(f"{r['status']:>10}  {r['artifact']}:{r['metric']} "
              f"base={r['baseline']:.10g} cur={cur}{extra}")

    cost_rows, cost_bad = [], []
    if costs_gated:
        from r2d2_tpu.telemetry.costmodel import compare_cost_tables
        cost_rows = compare_cost_tables(baseline_doc["costs"],
                                        current_costs(),
                                        rtol=args.costs_rtol)
        cost_bad = [r for r in cost_rows if r["status"] != "ok"]
        for r in cost_rows:
            if args.quiet and r["status"] == "ok":
                continue
            cur = "-" if r["current"] is None else f"{r['current']:.10g}"
            extra = (f"  ({r['delta_pct']:+}% vs an exact-match gate)"
                     if r["status"] == "CHANGED" else "")
            print(f"{r['status']:>10}  costs:{r['program']}.{r['metric']} "
                  f"base={r['baseline']:.10g} cur={cur}{extra}")

    print(f"-- {len(rows)} bench metric(s) checked, {len(bad)} failing; "
          f"{len(cost_rows)} cost metric(s) checked, "
          f"{len(cost_bad)} changed")
    return 1 if (bad or cost_bad) else 0


if __name__ == "__main__":
    sys.exit(main())
