"""Live run inspector: terminal dashboard over the telemetry stream,
plus Chrome-trace export of the recorded spans.

Reads what a training run leaves in ``runtime.save_dir``:

  * ``metrics_player{p}.jsonl``  — the per-interval aggregated records
    (throughput counters, health counters, and the telemetry 'stages'
    block with fleet-wide P50/P95/P99 per pipeline stage);
  * ``telemetry_host{r}.jsonl``  — per-host stage rows under multihost;
  * ``spans_*.jsonl``            — drained span events per process;
  * ``alerts_player{p}.jsonl``   — the sentinel's fired alerts (the
    record's ``alerts`` panel is the live view, this file the history).

On-device (anakin) runs render too: one metrics file, no heartbeat
board, the fused ``actor/act_scan`` stage — the fleet-health panel is
replaced by a mode tag instead of showing empty; a dp-sharded run adds
one row per shard (env steps / episodes / return sums) from the
record's ``anakin`` block.

Dashboard mode tails the records and redraws one screen per interval —
run it in a second terminal against a live soak. Export mode
(``--export-trace out.json``) merges every spans file into ONE
Chrome-trace JSON (each process a pid row, each thread a tid track) that
loads in Perfetto / chrome://tracing, viewable alongside the xprof
capture ``runtime.profile_at_step`` or SIGUSR2 triggered.

    python -m r2d2_tpu.tools.inspect --dir models               # once
    python -m r2d2_tpu.tools.inspect --dir models --follow      # live
    python -m r2d2_tpu.tools.inspect --dir models --export-trace t.json
"""

import glob
import json
import os
import sys
import time
from typing import List, Optional

from r2d2_tpu.tools.logparse import parse_jsonl

# stages in display order; anything else in the record appends after
_STAGE_ORDER = [
    "actor/act_scan",
    "actor/forward", "actor/env_step", "actor/block_emit",
    "actor/queue_put", "actor/weight_sync",
    "ingest/ring_get", "ingest/stage", "ingest/commit",
    "learner/sample", "learner/train_dispatch", "learner/device_sync",
    "learner/priority_writeback", "weights/publish",
]


def _fmt(v, width: int = 10) -> str:
    if v is None:
        return "-".rjust(width)
    if isinstance(v, float):
        return f"{v:.3f}".rjust(width)
    return str(v).rjust(width)


def render_record(record: dict, host_rows: Optional[List[dict]] = None,
                  costs: Optional[dict] = None,
                  roofline: Optional[dict] = None) -> str:
    """One dashboard frame from the newest aggregated record. ``costs``
    is the run's one-shot cost-model block (it rides exactly one record,
    so the caller digs it out of the stream's history); ``roofline`` the
    newest roofline artifact found next to the metrics (ISSUE 9)."""
    lines = []
    lines.append(
        f"t={record.get('t', 0):8.1f}s  "
        f"env_steps={record.get('env_steps', 0):>10}  "
        f"train_steps={record.get('training_steps', 0):>8}  "
        f"buffer={record.get('buffer_size', 0):>8}")
    lines.append(
        f"env-steps/s={record.get('buffer_speed') or 0.0:9.1f}  "
        f"updates/s={record.get('training_speed') or 0.0:7.2f}  "
        f"loss={_fmt(record.get('loss'), 8)}  "
        f"return={_fmt(record.get('avg_episode_return'), 8)}")
    stages = record.get("stages") or {}
    # on-device (anakin) runs have no actor fleet: one metrics file, no
    # heartbeat board, the fused 'actor/act_scan' stage instead of the
    # per-worker actor stages — label the mode instead of rendering
    # fleet-health panels that can only ever show empty
    on_device = "actor/act_scan" in stages
    health = [] if on_device else [
        f"{k.split('actor_')[-1]}={record[k]}" for k in (
            "actor_restarts", "actor_hangs_detected", "actor_breaker_trips",
            "actor_parked_slots") if record.get(k)]
    ingest = (f"ingest: blocks={record.get('ingest_blocks_total', 0)} "
              f"blocks/drain={_fmt(record.get('ingest_blocks_per_drain'), 6)}"
              f" queue={record.get('ingest_queue_depth', 0)} "
              f"pause={record.get('ingest_pause_time', 0.0)}s")
    if on_device:
        ingest = "mode: on-device (anakin, fused act+train)   " + ingest
    lines.append(ingest + ("   health: " + " ".join(health) if health else ""))
    an = record.get("anakin")
    if an:
        lines.append(render_anakin(an))
    lb = record.get("learning")
    if lb:
        lines.append("")
        lines.append(render_learning(lb))
    rd = record.get("replay_diag")
    if rd:
        lines.append("")
        lines.append(render_replay_diag(rd))
    rb = record.get("resources")
    if rb:
        lines.append("")
        lines.append(render_resources(rb))
    cb = costs or record.get("costs")
    if cb or roofline:
        lines.append("")
        lines.append(render_costs(cb, roofline))
    ab = record.get("alerts")
    if ab is not None:
        lines.append(render_alerts(ab))
    if stages:
        lines.append("")
        lines.append(f"{'stage':<28}{'count':>8}{'p50 ms':>10}"
                     f"{'p95 ms':>10}{'p99 ms':>10}")
        order = ([s for s in _STAGE_ORDER if s in stages]
                 + [s for s in sorted(stages) if s not in _STAGE_ORDER])
        for name in order:
            s = stages[name]
            lines.append(f"{name:<28}{s.get('count', 0):>8}"
                         f"{_fmt(s.get('p50_ms'))}{_fmt(s.get('p95_ms'))}"
                         f"{_fmt(s.get('p99_ms'))}")
        dropped = record.get("telemetry_dropped_spans")
        if dropped:
            lines.append(f"(spans dropped under ring pressure: {dropped})")
    else:
        lines.append("(no 'stages' block — telemetry.enabled=false, or a "
                     "pre-telemetry run)")
    for row in host_rows or []:
        n = len(row.get("stages") or {})
        lines.append(f"host rank {row.get('rank')}: {n} stages at "
                     f"t={row.get('t', 0):.1f}s "
                     f"(telemetry_host{row.get('rank')}.jsonl)")
    return "\n".join(lines)


def render_anakin(an: dict) -> str:
    """The sharded-anakin composition panel (ISSUE 8): one row per
    shard (env steps, episodes, return sums this interval) plus the
    env-step imbalance ratio the shard_imbalance alert watches."""
    imb = an.get("shard_imbalance")
    head = (f"anakin mesh: dp={an.get('dp')} "
            f"lanes/shard={an.get('lanes_per_shard')}"
            + (f"  imbalance={imb:.2f}" if imb is not None else ""))
    lines = [head]
    env = an.get("shard_env_steps") or []
    eps = an.get("shard_episodes") or []
    rep = an.get("shard_reported_episodes") or []
    ret = an.get("shard_return_sum") or []

    def at(seq, i):
        return seq[i] if i < len(seq) else None

    for i, steps in enumerate(env):
        bits = [f"  shard {i}: env-steps={steps}"]
        if at(eps, i) is not None:
            bits.append(f"episodes={eps[i]}")
        if at(rep, i) is not None:
            bits.append(f"reported={rep[i]}")
        if at(ret, i) is not None:
            bits.append(f"return-sum={ret[i]:.2f}")
        lines.append(" ".join(bits))
    return "\n".join(lines)


def render_replay_diag(rd: dict) -> str:
    """The replay-pathology panel (ISSUE 10): sum-tree health + collapse
    indicators (merged and, on a dp mesh, per shard), eviction lifetimes
    with the never-sampled fraction, and the ε-lane composition of the
    interval's sampled batches."""
    lines = []
    tree = rd.get("tree") or {}
    if tree:
        bits = [f"replay: tree active={tree.get('active_leaves')}"]
        if tree.get("ess_frac") is not None:
            bits.append(f"ess={tree.get('ess')} "
                        f"({100 * tree['ess_frac']:.0f}% of active)")
        if tree.get("max_mean_ratio") is not None:
            bits.append(f"max/mean={tree['max_mean_ratio']:.2f}")
        if tree.get("frac_at_max") is not None:
            bits.append(f"at-max={100 * tree['frac_at_max']:.0f}%")
        pr = tree.get("priorities") or {}
        if pr:
            bits.append(f"prio p50={pr['p50']:.4g} p95={pr['p95']:.4g}")
        lines.append(" ".join(bits))
    else:
        lines.append("replay: (no tree snapshot this interval)")
    for i, sh in enumerate(rd.get("shards") or []):
        if not sh:
            continue
        lines.append(f"  shard {i}: active={sh.get('active_leaves')} "
                     f"ess-frac={sh.get('ess_frac')} "
                     f"at-max={sh.get('frac_at_max')}")
    ev = rd.get("evictions") or {}
    if ev.get("evicted"):
        bits = [f"  evictions: {ev['evicted']} total"]
        if ev.get("never_sampled_frac") is not None:
            bits.append(f"NEVER-SAMPLED {100 * ev['never_sampled_frac']:.1f}%")
        if ev.get("mean_lifetime") is not None:
            bits.append(f"mean-lifetime={ev['mean_lifetime']:.2f}x")
        if ev.get("mean_age_blocks") is not None:
            bits.append(f"mean-age={ev['mean_age_blocks']:.0f} adds")
        it = ev.get("interval") or {}
        if it.get("evicted"):
            bits.append(f"(+{it['evicted']} this interval)")
        lines.append(" ".join(bits))
    ln = rd.get("lanes") or {}
    if ln:
        bits = [f"  lanes: {ln.get('active_lanes')}/{ln.get('total_lanes')}"
                f" active"]
        if ln.get("starved_frac"):
            bits.append(f"starved={100 * ln['starved_frac']:.0f}%")
        if ln.get("max_share") is not None:
            bits.append(f"top-lane share={100 * ln['max_share']:.0f}%")
        if ln.get("unknown_frac"):
            bits.append(f"unknown={100 * ln['unknown_frac']:.0f}%")
        lines.append(" ".join(bits))
    return "\n".join(lines)


def render_costs(cb: Optional[dict], roofline: Optional[dict]) -> str:
    """The cost-model / roofline panel (ISSUE 9): per-component FLOP
    shares from the run's one-shot ``costs`` block, joined with
    %-of-peak from the newest roofline artifact when one sits next to
    the metrics stream (tools/roofline.py --out)."""
    lines = []
    rl_comps = {}
    # the artifact is discovered by mtime alone (run dir or cwd) — guard
    # against joining a DIFFERENT shape's roofline (e.g. the gate-preset
    # ROOFLINE.json from `make roofline` next to a reference-shape run):
    # the record's costs block and the artifact both carry the analytic
    # model FLOPs, which pin the shape
    if roofline and cb and cb.get("model_flops_per_step"):
        rl_mfps = (roofline.get("parity") or {}).get("model_flops_per_step")
        if rl_mfps and abs(rl_mfps - cb["model_flops_per_step"]) \
                > 0.05 * cb["model_flops_per_step"]:
            lines.append("costs: (roofline artifact is for a different "
                         "shape — ignored; rerun `make roofline` against "
                         "this config)")
            roofline = None
    if roofline:
        ls = (roofline.get("learner_step") or {})
        rl_comps = ls.get("components") or {}
        peak = roofline.get("peak") or {}
        # name the artifact's preset in the header, and say so when the
        # run carries no costs block to validate the shape against (the
        # costmodel kill switch off) — mtime discovery must never let a
        # different-shape artifact masquerade as the live run's stats
        bits = [f"roofline[{roofline.get('preset', '?')}]"
                f"@{peak.get('device_kind', '?')}"]
        if not (cb or {}).get("model_flops_per_step"):
            bits.append("(shape unverified vs this run)")
        if ls.get("measured_ms"):
            bits.append(f"step={ls['measured_ms']:.2f}ms")
        if ls.get("pct_of_peak_total") is not None:
            bits.append(f"{ls['pct_of_peak_total']:.1f}% of peak")
        if peak.get("nominal"):
            bits.append("[nominal peaks]")
        par = (roofline.get("parity") or {}).get("ratio")
        if par is not None:
            bits.append(f"parity={par:.3f}")
        lines.append("costs: " + " ".join(bits))
    comps = (cb or {}).get("components") or rl_comps
    if comps:
        total = sum(c.get("flops", 0.0) for c in comps.values()) or 1.0
        row = []
        for name, c in sorted(comps.items(),
                              key=lambda kv: -kv[1].get("flops", 0.0)):
            bit = f"{name}={100 * c.get('flops', 0.0) / total:.0f}%"
            rc = rl_comps.get(name) or {}
            if rc.get("pct_of_peak") is not None:
                bit += f"({rc['pct_of_peak']:.1f}%pk)"
            row.append(bit)
        prefix = "  flops: " if lines else "costs: "
        lines.append(prefix + " ".join(row))
    if cb and cb.get("model_flops_per_step"):
        sc = cb.get("serial_chain") or {}
        lines.append(
            f"  model {cb['model_flops_per_step'] / 1e9:.3f} GFLOP/step"
            + (f"  serial chain {sc.get('iterations')} iters "
               f"({100 * sc.get('share_of_total', 0):.1f}% of FLOPs)"
               if sc else ""))
    return "\n".join(lines) if lines else "costs: (none)"


def render_learning(lb: dict) -> str:
    """The learning-dynamics panel (ISSUE 5): ΔQ, value-histogram
    percentiles, grad norms, staleness — one compact block per record."""
    lines = []
    dq = lb.get("delta_q") or {}
    if any(v is not None for v in dq.values()):
        lines.append(
            "learning: dQ stored={} zero={} recomputed={}".format(
                *(_fmt(dq.get(k), 8).strip()
                  for k in ("stored", "zero", "recomputed"))))
    else:
        lines.append("learning: (no dQ sample this interval)")
    row = []
    for label, key in (("|TD|", "td_abs"), ("prio", "priority"),
                       ("|Q|", "q_abs")):
        h = lb.get(key)
        if h:
            row.append(f"{label} p50={h['p50']:.4g} p95={h['p95']:.4g}")
    if row:
        lines.append("  " + "   ".join(row))
    gn = lb.get("grad_norm") or {}
    if gn:
        lines.append("  grad-norm " + " ".join(
            f"{k}={v.get('mean'):.4g}" for k, v in sorted(gn.items())
            if v.get("mean") is not None))
    age = lb.get("sample_age") or {}
    rage = lb.get("replay_age") or {}
    bits = []
    if age.get("p50") is not None:
        bits.append(f"sample-age p50={age['p50']:.0f} p95={age['p95']:.0f} "
                    f"max={age['max']}")
    if age.get("unknown_frac"):
        bits.append(f"unknown={100 * age['unknown_frac']:.0f}%")
    if rage.get("p50") is not None:
        bits.append(f"replay-age p50={rage['p50']:.0f} p95={rage['p95']:.0f}")
    if lb.get("target_param_dist") is not None:
        bits.append(f"target-dist={lb['target_param_dist']:.4g}")
    if bits:
        lines.append("  " + "   ".join(bits))
    if lb.get("nonfinite_steps"):
        lines.append(f"  !! NON-FINITE steps this interval: "
                     f"{lb['nonfinite_steps']} (see nan_dump_player*.json)")
    return "\n".join(lines)


def render_resources(rb: dict) -> str:
    """The machine-side panel (ISSUE 7): per-device HBM + headroom, host
    RSS/CPU, the buffer-attribution table, and the compile/retrace
    sub-block — one compact block per record."""
    lines = []
    devs = rb.get("devices") or []
    dev_bits = []
    for d in devs[:4]:
        if d.get("bytes_in_use") is None:
            continue
        bit = f"dev{d.get('id')}={d['bytes_in_use'] / 2**20:.0f}MiB"
        if d.get("headroom_frac") is not None:
            bit += f" ({100 * d['headroom_frac']:.0f}% free)"
        dev_bits.append(bit)
    host = rb.get("host") or {}
    host_bits = []
    if host.get("rss_bytes") is not None:
        host_bits.append(f"rss={host['rss_bytes'] / 2**20:.0f}MiB")
    if host.get("cpu_pct") is not None:
        host_bits.append(f"cpu={host['cpu_pct']:.0f}%")
    if host.get("threads") is not None:
        host_bits.append(f"threads={host['threads']}")
    lines.append("resources: "
                 + (" ".join(dev_bits) if dev_bits
                    else "(no device byte counters — CPU backend)")
                 + ("   host: " + " ".join(host_bits) if host_bits else ""))
    slots = rb.get("actor_slots") or {}
    if slots.get("rss_bytes"):
        rss = [f"{b / 2**20:.0f}" for b in slots["rss_bytes"]]
        cpu = ["-" if c is None else f"{c:.0f}"
               for c in slots.get("cpu_pct") or []]
        lines.append(f"  actor slots rss MiB: [{' '.join(rss)}]"
                     + (f"  cpu %: [{' '.join(cpu)}]" if cpu else ""))
    bufs = rb.get("buffers") or {}
    if bufs:
        top = sorted(bufs.items(), key=lambda kv: -kv[1])[:6]
        lines.append("  buffers: " + " ".join(
            f"{name}={b / 2**20:.0f}MiB" for name, b in top)
            + f"  total={rb.get('buffers_total', 0) / 2**20:.0f}MiB")
    comp = rb.get("compile")
    if comp:
        line = (f"  compile: total={comp.get('compiles_total', 0)} "
                f"({comp.get('compile_time_s_total', 0.0):.1f}s) "
                f"interval={comp.get('compiles', 0)} "
                f"retraces={comp.get('retraces_total', 0)}"
                + (" [warm]" if comp.get("warm") else " [warming up]"))
        aot = comp.get("aot") or {}
        if aot.get("missing"):
            line += f"  !! AOT buckets missing: {aot['missing']}"
        lines.append(line)
        last = comp.get("last_retrace")
        if comp.get("retraces_interval") and last:
            lines.append(f"  !! RETRACE {last.get('fn')} "
                         f"{(last.get('avals') or '')[:80]}")
    return "\n".join(lines)


def render_alerts(ab: dict) -> str:
    """The sentinel panel (ISSUE 7): rules active now + firings this
    interval; silent when everything is healthy."""
    active = ab.get("active") or []
    fired = ab.get("fired") or []
    if not active and not fired:
        return "alerts: none active"
    lines = [f"alerts ACTIVE: {' '.join(active)}"]
    for a in fired:
        bit = f"  -> FIRED {a.get('severity', '?').upper()} {a.get('rule')}"
        if a.get("value") is not None:
            bit += f" value={a['value']:.4g} bound={a.get('bound')}"
        if a.get("baseline") is not None:
            bit += f" baseline={a['baseline']:.4g}"
        lines.append(bit)
    return "\n".join(lines)


def newest_roofline(run_dir: str) -> Optional[dict]:
    """The newest roofline artifact next to the metrics stream (or in
    the working directory — where `make roofline` drops it)."""
    paths = [p for d in (run_dir, ".") for pat in
             ("ROOFLINE*.json", "roofline*.json")
             for p in glob.glob(os.path.join(d, pat))]
    if not paths:
        return None
    try:
        # getmtime inside the guard: a follow-mode dashboard can race a
        # `make roofline` rewrite (or a deletion) between glob and stat
        with open(max(set(paths), key=os.path.getmtime)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def costs_record(records: List[dict]) -> Optional[dict]:
    """The one-shot ``costs`` block from wherever in the stream it rode
    (the first record after the learner's first flush)."""
    for rec in reversed(records):
        if rec.get("costs"):
            return rec["costs"]
    return None


def newest_host_rows(run_dir: str) -> List[dict]:
    rows = []
    for path in sorted(glob.glob(os.path.join(run_dir,
                                              "telemetry_host*.jsonl"))):
        recs = parse_jsonl(path, limit=1)
        if recs:
            rows.append(recs[-1])
    return rows


def export_chrome_trace(run_dir: str, out_path: str) -> int:
    """Merge every spans_*.jsonl under ``run_dir`` into one Chrome-trace
    JSON; returns the number of span events exported."""
    from r2d2_tpu.telemetry import chrome_trace_events
    events = []
    n = 0
    for pid_index, path in enumerate(
            sorted(glob.glob(os.path.join(run_dir, "spans_*.jsonl")))):
        spans = parse_jsonl(path)
        n += len(spans)
        pid = (spans[0].get("pid") if spans else None) or \
            os.path.basename(path)[len("spans_"):-len(".jsonl")]
        events.extend(chrome_trace_events(spans, pid, pid_index))
    with open(out_path, "w") as f:
        json.dump({"traceEvents": events,
                   "displayTimeUnit": "ms"}, f)
    return n


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--dir", default="models",
                   help="the run's save_dir (metrics/spans live there)")
    p.add_argument("--player", type=int, default=0)
    p.add_argument("--follow", action="store_true",
                   help="keep tailing and redraw per new record")
    p.add_argument("--interval", type=float, default=2.0,
                   help="poll cadence in follow mode")
    p.add_argument("--export-trace", default="",
                   help="write Chrome-trace JSON here (Perfetto-loadable) "
                        "and exit")
    args = p.parse_args(argv)

    if args.export_trace:
        n = export_chrome_trace(args.dir, args.export_trace)
        print(f"exported {n} spans from {args.dir!r} to "
              f"{args.export_trace!r}")
        return 0

    path = os.path.join(args.dir, f"metrics_player{args.player}.jsonl")
    last_len = -1
    while True:
        try:
            records = parse_jsonl(path)
        except FileNotFoundError:
            print(f"waiting for {path} ..." if args.follow
                  else f"no metrics stream at {path}")
            if not args.follow:
                return 1
            time.sleep(args.interval)
            continue
        if records and len(records) != last_len:
            last_len = len(records)
            frame = render_record(records[-1], newest_host_rows(args.dir),
                                  costs=costs_record(records),
                                  roofline=newest_roofline(args.dir))
            if args.follow and sys.stdout.isatty():
                sys.stdout.write("\x1b[2J\x1b[H")   # clear + home
            print(f"== {path} (record {len(records)}) ==")
            print(frame, flush=True)
            # the alert stream's newest firings (machine-readable side of
            # the record's 'alerts' panel; absent pre-PR7 or with the
            # pillar off)
            apath = os.path.join(args.dir,
                                 f"alerts_player{args.player}.jsonl")
            if os.path.exists(apath):
                for row in parse_jsonl(apath, limit=3):
                    print(f"  alert@t={row.get('t', 0):.0f}s "
                          f"{row.get('severity', '?')}: {row.get('rule')} "
                          f"value={row.get('value')}", flush=True)
        if not args.follow:
            return 0
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
