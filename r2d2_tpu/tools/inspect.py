"""Live run inspector: terminal dashboard over the telemetry stream,
plus Chrome-trace export of the recorded spans.

Reads what a training run leaves in ``runtime.save_dir``:

  * ``metrics_player{p}.jsonl``  — the per-interval aggregated records
    (throughput counters, health counters, and the telemetry 'stages'
    block with fleet-wide P50/P95/P99 per pipeline stage);
  * ``telemetry_host{r}.jsonl``  — per-host stage rows under multihost
    (fleet mode widens them: lockstep timing, mergeable stage counts,
    clock anchors, per-rank alert state — rendered as the per-rank
    panel, and the anchors align the cross-host trace merge);
  * ``spans_*.jsonl``            — drained span events per process;
  * ``alerts_player{p}.jsonl``   — the sentinel's fired alerts (the
    record's ``alerts`` panel is the live view, this file the history).

On-device (anakin) runs render too: one metrics file, no heartbeat
board, the fused ``actor/act_scan`` stage — the fleet-health panel is
replaced by a mode tag instead of showing empty; a dp-sharded run adds
one row per shard (env steps / episodes / return sums) from the
record's ``anakin`` block.

Dashboard mode tails the records and redraws one screen per interval —
run it in a second terminal against a live soak. Export mode
(``--export-trace out.json``) merges every spans file into ONE
Chrome-trace JSON (each process a pid row, each thread a tid track) that
loads in Perfetto / chrome://tracing, viewable alongside the xprof
capture ``runtime.profile_at_step`` or SIGUSR2 triggered. The merge
spans every PLANE of a disaggregated run (ISSUE 19): learner + actor
spans, the policy server's ``spans_serve.jsonl``, and a standalone
ReplayService's ``spans_replay_service.jsonl`` land on one timeline,
aligned per the clock anchors their processes stamped at lease
announcement (``plane_clock_offsets``; cross-host rank spans keep the
PR-12 host-anchor shift).

    python -m r2d2_tpu.tools.inspect --dir models               # once
    python -m r2d2_tpu.tools.inspect --dir models --follow      # live
    python -m r2d2_tpu.tools.inspect --dir models --export-trace t.json
"""

import glob
import json
import os
import sys
import time
from typing import List, Optional

from r2d2_tpu.telemetry.fleet import read_last_jsonl_row
from r2d2_tpu.tools.logparse import parse_jsonl

# stages in display order; anything else in the record appends after
_STAGE_ORDER = [
    "actor/act_scan",
    "actor/forward", "actor/env_step", "actor/block_emit",
    "actor/queue_put", "actor/weight_sync",
    "ingest/ring_get", "ingest/stage", "ingest/commit",
    "learner/sample", "learner/train_dispatch", "learner/device_sync",
    "learner/priority_writeback", "weights/publish",
    "lockstep/dispatch", "lockstep/step",
    "serve/enqueue", "serve/batch_wait", "serve/forward", "serve/reply",
]


def _fmt(v, width: int = 10) -> str:
    if v is None:
        return "-".rjust(width)
    if isinstance(v, float):
        return f"{v:.3f}".rjust(width)
    return str(v).rjust(width)


def render_record(record: dict, host_rows: Optional[List[dict]] = None,
                  costs: Optional[dict] = None,
                  roofline: Optional[dict] = None) -> str:
    """One dashboard frame from the newest aggregated record. ``costs``
    is the run's one-shot cost-model block (it rides exactly one record,
    so the caller digs it out of the stream's history); ``roofline`` the
    newest roofline artifact found next to the metrics (ISSUE 9)."""
    lines = []
    lines.append(
        f"t={record.get('t', 0):8.1f}s  "
        f"env_steps={record.get('env_steps', 0):>10}  "
        f"train_steps={record.get('training_steps', 0):>8}  "
        f"buffer={record.get('buffer_size', 0):>8}")
    lines.append(
        f"env-steps/s={record.get('buffer_speed') or 0.0:9.1f}  "
        f"updates/s={record.get('training_speed') or 0.0:7.2f}  "
        f"loss={_fmt(record.get('loss'), 8)}  "
        f"return={_fmt(record.get('avg_episode_return'), 8)}")
    stages = record.get("stages") or {}
    # on-device (anakin) runs have no actor fleet: one metrics file, no
    # heartbeat board, the fused 'actor/act_scan' stage instead of the
    # per-worker actor stages — label the mode instead of rendering
    # fleet-health panels that can only ever show empty
    on_device = "actor/act_scan" in stages
    health = [] if on_device else [
        f"{k.split('actor_')[-1]}={record[k]}" for k in (
            "actor_restarts", "actor_hangs_detected", "actor_breaker_trips",
            "actor_parked_slots") if record.get(k)]
    ingest = (f"ingest: blocks={record.get('ingest_blocks_total', 0)} "
              f"blocks/drain={_fmt(record.get('ingest_blocks_per_drain'), 6)}"
              f" queue={record.get('ingest_queue_depth', 0)} "
              f"pause={record.get('ingest_pause_time', 0.0)}s")
    if on_device:
        ingest = "mode: on-device (anakin, fused act+train)   " + ingest
    lines.append(ingest + ("   health: " + " ".join(health) if health else ""))
    an = record.get("anakin")
    if an:
        lines.append(render_anakin(an, record.get("quant")))
    fb = record.get("fleet")
    if fb:
        lines.append("")
        lines.append(render_fleet(fb))
    lb = record.get("learning")
    if lb:
        lines.append("")
        lines.append(render_learning(lb))
    rd = record.get("replay_diag")
    if rd:
        lines.append("")
        lines.append(render_replay_diag(rd))
    sv = record.get("serving")
    if sv:
        lines.append("")
        lines.append(render_serving(sv, record.get("quant")))
    qb = record.get("quant")
    if qb and not sv:
        # quantized LOCAL/anakin inference (no serving panel to ride):
        # the dtype + live agreement gauge get their own line
        lines.append("")
        lines.append(render_quant(qb))
    qy = record.get("quality")
    if qy:
        lines.append("")
        lines.append(render_quality(qy))
    tb = record.get("trace")
    if tb:
        lines.append("")
        lines.append(render_trace(tb))
    rb = record.get("resources")
    if rb:
        lines.append("")
        lines.append(render_resources(rb))
    cb = costs or record.get("costs")
    if cb or roofline:
        lines.append("")
        lines.append(render_costs(cb, roofline))
    ab = record.get("alerts")
    if ab is not None:
        lines.append(render_alerts(ab))
    if stages:
        lines.append("")
        lines.append(f"{'stage':<28}{'count':>8}{'p50 ms':>10}"
                     f"{'p95 ms':>10}{'p99 ms':>10}")
        order = ([s for s in _STAGE_ORDER if s in stages]
                 + [s for s in sorted(stages) if s not in _STAGE_ORDER])
        for name in order:
            s = stages[name]
            lines.append(f"{name:<28}{s.get('count', 0):>8}"
                         f"{_fmt(s.get('p50_ms'))}{_fmt(s.get('p95_ms'))}"
                         f"{_fmt(s.get('p99_ms'))}")
        dropped = record.get("telemetry_dropped_spans")
        if dropped:
            lines.append(f"(spans dropped under ring pressure: {dropped})")
    else:
        lines.append("(no 'stages' block — telemetry.enabled=false, or a "
                     "pre-telemetry run)")
    if host_rows:
        lines.append("")
        lines.append(render_host_rows(host_rows))
    return "\n".join(lines)


def render_fleet(fb: dict) -> str:
    """The fleet panel (ISSUE 12): per-rank step-time table with the
    straggler called out, lockstep-wait fraction, env-step divergence,
    and host-row health — the record's ``fleet`` block."""
    lines = [f"fleet: {fb.get('ranks')} rank(s), "
             f"{fb.get('iters')} lockstep iters"]
    ls = fb.get("lockstep") or {}
    if ls.get("wait_frac") is not None:
        lines[0] += (f"  wait={100 * ls['wait_frac']:.0f}% of step "
                     f"(dispatch p~{_fmt(ls.get('wait_ms_mean'), 1).strip()}"
                     f"ms, step {_fmt(ls.get('step_ms_mean'), 1).strip()}ms)")
    st = fb.get("step_time") or {}
    per = st.get("per_rank_ms") or []
    if per:
        straggler = st.get("straggler_rank")
        cells = [f"r{i}={v:.1f}{'*' if i == straggler else ''}"
                 for i, v in enumerate(per)]
        line = "  step-time ms: " + " ".join(cells)
        if st.get("skew") is not None:
            line += f"   skew={st['skew']:.2f}"
        if straggler is not None:
            line += f"  straggler=rank {straggler}"
        lines.append(line)
    env = fb.get("env_steps") or {}
    if env.get("interval"):
        line = ("  env-steps this interval: "
                + " ".join(f"r{i}={v}"
                           for i, v in enumerate(env["interval"])))
        if env.get("divergence") is not None:
            line += f"   divergence={env['divergence']:.2f}"
        lines.append(line)
    hr = fb.get("host_rows") or {}
    if hr:
        bits = []
        if hr.get("max_age_s") is not None:
            bits.append(f"stalest row {hr['max_age_s']:.1f}s")
        if hr.get("absent_ranks"):
            bits.append(f"ABSENT ranks {hr['absent_ranks']}")
        if bits:
            lines.append("  host rows: " + " ".join(bits))
    return "\n".join(lines)


def render_host_rows(host_rows: List[dict]) -> str:
    """The per-rank panel (ISSUE 12): one line per host row — stage P99
    peaks, HBM headroom, step-time/wait view, and alert state — instead
    of the old one-line 'N stages' summary."""
    lines = ["per-rank (telemetry_host*.jsonl):"]
    for row in host_rows:
        stages = row.get("stages") or {}
        bits = [f"  rank {row.get('rank')}: t={row.get('t', 0):.1f}s"]
        # the three slowest stages by P99 — where this rank's time goes
        top = sorted(((s.get("p99_ms") or 0.0, name)
                      for name, s in stages.items()), reverse=True)[:3]
        if top:
            bits.append("p99 " + " ".join(
                f"{name.split('/')[-1]}={p99:.1f}ms"
                for p99, name in top))
        rb = row.get("resources") or {}
        if rb.get("hbm_headroom_frac_min") is not None:
            bits.append(f"hbm-free={100 * rb['hbm_headroom_frac_min']:.0f}%")
        fb = (row.get("fleet") or {})
        ls = fb.get("lockstep") or {}
        if ls.get("wait_frac") is not None:
            bits.append(f"wait={100 * ls['wait_frac']:.0f}%")
        st = fb.get("step_time") or {}
        if st.get("skew") is not None:
            bits.append(f"skew={st['skew']:.2f}")
        ab = row.get("alerts")
        if ab is not None:
            active = ab.get("active") or []
            bits.append("alerts: " + (" ".join(active) if active
                                      else "none"))
        lines.append(" ".join(bits))
    return "\n".join(lines)


def render_anakin(an: dict, quant: Optional[dict] = None) -> str:
    """The sharded-anakin composition panel (ISSUE 8): one row per
    shard (env steps, episodes, return sums this interval) plus the
    env-step imbalance ratio the shard_imbalance alert watches. A
    quantized acting scan (ISSUE 14) adds the active inference dtype to
    the head line (the agreement gauge renders as its own quant line)."""
    imb = an.get("shard_imbalance")
    head = (f"anakin mesh: dp={an.get('dp')} "
            f"lanes/shard={an.get('lanes_per_shard')}"
            + (f"  imbalance={imb:.2f}" if imb is not None else "")
            + (f"  inference={quant.get('dtype')}" if quant else ""))
    lines = [head]
    env = an.get("shard_env_steps") or []
    eps = an.get("shard_episodes") or []
    rep = an.get("shard_reported_episodes") or []
    ret = an.get("shard_return_sum") or []

    def at(seq, i):
        return seq[i] if i < len(seq) else None

    for i, steps in enumerate(env):
        bits = [f"  shard {i}: env-steps={steps}"]
        if at(eps, i) is not None:
            bits.append(f"episodes={eps[i]}")
        if at(rep, i) is not None:
            bits.append(f"reported={rep[i]}")
        if at(ret, i) is not None:
            bits.append(f"return-sum={ret[i]:.2f}")
        lines.append(" ".join(bits))
    return "\n".join(lines)


def render_quant(qb: dict) -> str:
    """The quantized-inference gauge (ISSUE 14): active inference dtype
    + the interval's live f32-twin agreement / max |ΔQ| probes — the
    record's ``quant`` block."""
    bits = [f"quant: dtype={qb.get('dtype')}"]
    if qb.get("probes"):
        bits.append(f"probes={qb['probes']}")
        if qb.get("agree_frac") is not None:
            bits.append(f"agree={100 * qb['agree_frac']:.1f}%")
        if qb.get("agree_min") is not None:
            bits.append(f"(min {100 * qb['agree_min']:.0f}%)")
        if qb.get("dq_max") is not None:
            bits.append(f"|dQ|max={qb['dq_max']:.4g}")
    else:
        bits.append("no probes this interval")
    if qb.get("publish_stamp"):
        bits.append(f"twin@pub={qb['publish_stamp']}")
    return " ".join(bits)


def render_serving(sv: dict, quant: Optional[dict] = None) -> str:
    """The serving panel (ISSUE 13): request latency percentiles, batch
    fill, dispatch causes, and client lease churn — the record's
    ``serving`` block from the central policy inference server. When the
    run serves a quantized forward (ISSUE 14), the active inference
    dtype + live agreement gauge render as the panel's last line."""
    lat = sv.get("latency") or {}
    batch = sv.get("batch") or {}
    clients = sv.get("clients") or {}
    lines = [f"serving: {sv.get('requests', 0)} req "
             f"{sv.get('replies', 0)} ok "
             f"{sv.get('expired', 0)} expired "
             f"{sv.get('timeouts', 0)} timeouts(cum)  "
             f"clients={clients.get('active', 0)}"]
    if lat:
        lines.append(
            f"  latency ms: p50={_fmt(lat.get('p50_ms'), 8).strip()} "
            f"p95={_fmt(lat.get('p95_ms'), 8).strip()} "
            f"p99={_fmt(lat.get('p99_ms'), 8).strip()}"
            + (f"   SLO deadline {sv['deadline_ms']}ms"
               if sv.get("deadline_ms") is not None else ""))
    if batch.get("count"):
        bits = [f"  batches={batch['count']} "
                f"fill={_fmt(batch.get('fill_mean'), 6).strip()}"
                f"/{sv.get('max_batch', '-')}"]
        for key, label in (("full_frac", "full"),
                           ("deadline_frac", "deadline"),
                           ("starved_frac", "starved")):
            if batch.get(key) is not None:
                bits.append(f"{label}={100 * batch[key]:.0f}%")
        lines.append(" ".join(bits))
    churn = [f"{k}={clients[k]}" for k in
             ("connects", "reconnects", "disconnects", "evictions")
             if clients.get(k)]
    if churn:
        lines.append("  leases: " + " ".join(churn))
    adm = sv.get("admission")
    if adm:
        alat = adm.get("admitted_latency") or {}
        bits = [f"  admission: shed={adm.get('shed', 0)} "
                f"({100 * adm.get('shed_frac', 0.0):.1f}%) "
                f"misrouted={adm.get('misrouted', 0)}"]
        if alat.get("p99_ms") is not None:
            bits.append(f"admitted p99={_fmt(alat['p99_ms'], 8).strip()}ms")
        lines.append(" ".join(bits))
    fleet = sv.get("servers")
    if fleet:
        lines.append(f"  fleet: {fleet.get('count', 0)} servers "
                     f"map v{fleet.get('map_version', 0)}")
        for slot, row in sorted((fleet.get("rows") or {}).items(),
                                key=lambda kv: int(kv[0])):
            lines.append(
                f"    server {slot}: {row.get('requests', 0)} req "
                f"fill={_fmt(row.get('fill_mean'), 6).strip()} "
                f"p50={_fmt(row.get('latency_p50_ms'), 8).strip()} "
                f"p99={_fmt(row.get('latency_p99_ms'), 8).strip()} "
                f"shed={row.get('shed', 0)} "
                f"shards={row.get('shards', 0)}")
    if quant:
        lines.append("  " + render_quant(quant))
    return "\n".join(lines)


def render_replay_diag(rd: dict) -> str:
    """The replay-pathology panel (ISSUE 10): sum-tree health + collapse
    indicators (merged and, on a dp mesh, per shard), eviction lifetimes
    with the never-sampled fraction, and the ε-lane composition of the
    interval's sampled batches."""
    lines = []
    tree = rd.get("tree") or {}
    if tree:
        bits = [f"replay: tree active={tree.get('active_leaves')}"]
        if tree.get("ess_frac") is not None:
            bits.append(f"ess={tree.get('ess')} "
                        f"({100 * tree['ess_frac']:.0f}% of active)")
        if tree.get("max_mean_ratio") is not None:
            bits.append(f"max/mean={tree['max_mean_ratio']:.2f}")
        if tree.get("frac_at_max") is not None:
            bits.append(f"at-max={100 * tree['frac_at_max']:.0f}%")
        pr = tree.get("priorities") or {}
        if pr:
            bits.append(f"prio p50={pr['p50']:.4g} p95={pr['p95']:.4g}")
        lines.append(" ".join(bits))
    else:
        lines.append("replay: (no tree snapshot this interval)")
    for i, sh in enumerate(rd.get("shards") or []):
        if not sh:
            continue
        lines.append(f"  shard {i}: active={sh.get('active_leaves')} "
                     f"ess-frac={sh.get('ess_frac')} "
                     f"at-max={sh.get('frac_at_max')}")
    ev = rd.get("evictions") or {}
    if ev.get("evicted"):
        bits = [f"  evictions: {ev['evicted']} total"]
        if ev.get("never_sampled_frac") is not None:
            bits.append(f"NEVER-SAMPLED {100 * ev['never_sampled_frac']:.1f}%")
        if ev.get("mean_lifetime") is not None:
            bits.append(f"mean-lifetime={ev['mean_lifetime']:.2f}x")
        if ev.get("mean_age_blocks") is not None:
            bits.append(f"mean-age={ev['mean_age_blocks']:.0f} adds")
        it = ev.get("interval") or {}
        if it.get("evicted"):
            bits.append(f"(+{it['evicted']} this interval)")
        lines.append(" ".join(bits))
    ln = rd.get("lanes") or {}
    if ln:
        bits = [f"  lanes: {ln.get('active_lanes')}/{ln.get('total_lanes')}"
                f" active"]
        if ln.get("starved_frac"):
            bits.append(f"starved={100 * ln['starved_frac']:.0f}%")
        if ln.get("max_share") is not None:
            bits.append(f"top-lane share={100 * ln['max_share']:.0f}%")
        if ln.get("unknown_frac"):
            bits.append(f"unknown={100 * ln['unknown_frac']:.0f}%")
        lines.append(" ".join(bits))
    return "\n".join(lines)


def render_costs(cb: Optional[dict], roofline: Optional[dict]) -> str:
    """The cost-model / roofline panel (ISSUE 9): per-component FLOP
    shares from the run's one-shot ``costs`` block, joined with
    %-of-peak from the newest roofline artifact when one sits next to
    the metrics stream (tools/roofline.py --out)."""
    lines = []
    rl_comps = {}
    # the artifact is discovered by mtime alone (run dir or cwd) — guard
    # against joining a DIFFERENT shape's roofline (e.g. the gate-preset
    # ROOFLINE.json from `make roofline` next to a reference-shape run):
    # the record's costs block and the artifact both carry the analytic
    # model FLOPs, which pin the shape
    if roofline and cb and cb.get("model_flops_per_step"):
        rl_mfps = (roofline.get("parity") or {}).get("model_flops_per_step")
        if rl_mfps and abs(rl_mfps - cb["model_flops_per_step"]) \
                > 0.05 * cb["model_flops_per_step"]:
            lines.append("costs: (roofline artifact is for a different "
                         "shape — ignored; rerun `make roofline` against "
                         "this config)")
            roofline = None
    if roofline:
        ls = (roofline.get("learner_step") or {})
        rl_comps = ls.get("components") or {}
        peak = roofline.get("peak") or {}
        # name the artifact's preset in the header, and say so when the
        # run carries no costs block to validate the shape against (the
        # costmodel kill switch off) — mtime discovery must never let a
        # different-shape artifact masquerade as the live run's stats
        bits = [f"roofline[{roofline.get('preset', '?')}]"
                f"@{peak.get('device_kind', '?')}"]
        if not (cb or {}).get("model_flops_per_step"):
            bits.append("(shape unverified vs this run)")
        if ls.get("measured_ms"):
            bits.append(f"step={ls['measured_ms']:.2f}ms")
        if ls.get("pct_of_peak_total") is not None:
            bits.append(f"{ls['pct_of_peak_total']:.1f}% of peak")
        if peak.get("nominal"):
            bits.append("[nominal peaks]")
        par = (roofline.get("parity") or {}).get("ratio")
        if par is not None:
            bits.append(f"parity={par:.3f}")
        lines.append("costs: " + " ".join(bits))
    comps = (cb or {}).get("components") or rl_comps
    if comps:
        total = sum(c.get("flops", 0.0) for c in comps.values()) or 1.0
        row = []
        for name, c in sorted(comps.items(),
                              key=lambda kv: -kv[1].get("flops", 0.0)):
            bit = f"{name}={100 * c.get('flops', 0.0) / total:.0f}%"
            rc = rl_comps.get(name) or {}
            if rc.get("pct_of_peak") is not None:
                bit += f"({rc['pct_of_peak']:.1f}%pk)"
            row.append(bit)
        prefix = "  flops: " if lines else "costs: "
        lines.append(prefix + " ".join(row))
    if cb and cb.get("model_flops_per_step"):
        sc = cb.get("serial_chain") or {}
        lines.append(
            f"  model {cb['model_flops_per_step'] / 1e9:.3f} GFLOP/step"
            + (f"  serial chain {sc.get('iterations')} iters "
               f"({100 * sc.get('share_of_total', 0):.1f}% of FLOPs)"
               if sc else ""))
    return "\n".join(lines) if lines else "costs: (none)"


def render_learning(lb: dict) -> str:
    """The learning-dynamics panel (ISSUE 5): ΔQ, value-histogram
    percentiles, grad norms, staleness — one compact block per record."""
    lines = []
    dq = lb.get("delta_q") or {}
    if any(v is not None for v in dq.values()):
        lines.append(
            "learning: dQ stored={} zero={} recomputed={}".format(
                *(_fmt(dq.get(k), 8).strip()
                  for k in ("stored", "zero", "recomputed"))))
    else:
        lines.append("learning: (no dQ sample this interval)")
    row = []
    for label, key in (("|TD|", "td_abs"), ("prio", "priority"),
                       ("|Q|", "q_abs")):
        h = lb.get(key)
        if h:
            row.append(f"{label} p50={h['p50']:.4g} p95={h['p95']:.4g}")
    if row:
        lines.append("  " + "   ".join(row))
    gn = lb.get("grad_norm") or {}
    if gn:
        lines.append("  grad-norm " + " ".join(
            f"{k}={v.get('mean'):.4g}" for k, v in sorted(gn.items())
            if v.get("mean") is not None))
    age = lb.get("sample_age") or {}
    rage = lb.get("replay_age") or {}
    bits = []
    if age.get("p50") is not None:
        bits.append(f"sample-age p50={age['p50']:.0f} p95={age['p95']:.0f} "
                    f"max={age['max']}")
    if age.get("unknown_frac"):
        bits.append(f"unknown={100 * age['unknown_frac']:.0f}%")
    if rage.get("p50") is not None:
        bits.append(f"replay-age p50={rage['p50']:.0f} p95={rage['p95']:.0f}")
    if lb.get("target_param_dist") is not None:
        bits.append(f"target-dist={lb['target_param_dist']:.4g}")
    if bits:
        lines.append("  " + "   ".join(bits))
    if lb.get("nonfinite_steps"):
        lines.append(f"  !! NON-FINITE steps this interval: "
                     f"{lb['nonfinite_steps']} (see nan_dump_player*.json)")
    return "\n".join(lines)


def render_quality(qy: dict) -> str:
    """The policy-quality panel (ISSUE 20): continuous-eval return per
    scenario, the in-stream Q-calibration gauge (greedy max-Q at
    decision time vs realized n-step return), shadow-scoring divergence
    against a canary candidate, and the promotion state machine — the
    record's ``quality`` block."""
    ev = qy.get("eval") or {}
    cal = qy.get("calibration") or {}
    sh = qy.get("shadow") or {}
    pr = qy.get("promotion") or {}
    head = "quality:"
    if ev.get("mean_return") is not None:
        head += (f" eval={ev['mean_return']:.2f}"
                 + (f" (ckpt step {ev['checkpoint_step']})"
                    if ev.get("checkpoint_step") is not None else "")
                 + (f" stamp={ev['publish_stamp']}"
                    if ev.get("publish_stamp") is not None else "")
                 + (f"<-{ev['parent_stamp']}"
                    if ev.get("parent_stamp") is not None else ""))
    else:
        head += " (no eval rollout yet)"
    if ev.get("evals_total"):
        head += f"  evals={ev['evals_total']}"
    lines = [head]
    for row in ev.get("scenarios") or []:
        lines.append(f"  scenario {row.get('scenario')}: "
                     f"mean={_fmt(row.get('mean_return'), 8).strip()} "
                     f"min={_fmt(row.get('min_return'), 8).strip()} "
                     f"max={_fmt(row.get('max_return'), 8).strip()} "
                     f"({row.get('episodes', 0)} ep)")
    if cal.get("samples"):
        lines.append(
            f"  calibration: {cal['samples']} joined sample(s) "
            f"gap={_fmt(cal.get('gap_mean'), 8).strip()}"
            + (f" |gap|max={_fmt(cal.get('gap_abs_max'), 8).strip()}"
               if cal.get("gap_abs_max") is not None else "")
            + (f" stamp={cal['stamp']}"
               if cal.get("stamp") is not None else "")
            + f" (total {cal.get('samples_total', 0)})")
    if sh.get("requests"):
        bits = [f"  shadow: {sh['requests']} scored"]
        if sh.get("divergence") is not None:
            bits.append(f"divergence={sh['divergence']:.3f}")
        if sh.get("agree_frac") is not None:
            bits.append(f"agree={100 * sh['agree_frac']:.1f}%")
        if sh.get("dq_max") is not None:
            bits.append(f"|dQ|max={sh['dq_max']:.4g}")
        if sh.get("dropped"):
            bits.append(f"dropped={sh['dropped']}")
        bits.append(f"(total {sh.get('mirrored_total', 0)})")
        lines.append(" ".join(bits))
    if pr.get("state") and pr["state"] != "idle":
        bits = [f"  promotion: {pr['state'].upper()}"]
        if pr.get("age_s") is not None:
            bits.append(f"age={pr['age_s']:.0f}s")
        if pr.get("candidate_stamp") is not None:
            bits.append(f"candidate={pr['candidate_stamp']}")
        if pr.get("previous_stamp") is not None:
            bits.append(f"previous={pr['previous_stamp']}")
        counts = [f"{k}={pr[k]}" for k in
                  ("promotions", "rollbacks", "refusals") if pr.get(k)]
        if counts:
            bits.append(" ".join(counts))
        lines.append(" ".join(bits))
    return "\n".join(lines)


def render_trace(tb: dict) -> str:
    """The cross-plane tracing panel (ISSUE 19): the end-to-end
    env-step -> gradient latency of the interval's lineage-stamped
    blocks, broken down per pipeline hop — the record's ``trace``
    block."""
    e2e = tb.get("e2e_experience_latency") or {}
    head = f"trace: {tb.get('sampled', 0)} sampled row(s)"
    if e2e.get("p50_ms") is not None:
        head += (f"  e2e env-step->gradient ms: p50={e2e['p50_ms']:.0f} "
                 f"p95={e2e['p95_ms']:.0f} p99={e2e['p99_ms']:.0f}")
    lines = [head]
    hops = tb.get("hops") or {}
    if hops:
        bits = []
        for name in ("emit_to_ingest", "ingest_to_sample",
                     "sample_to_train"):
            h = hops.get(name)
            if h and h.get("p50_ms") is not None:
                bits.append(f"{name}={h['p50_ms']:.0f}ms")
        if bits:
            lines.append("  hops p50: " + " ".join(bits))
    return "\n".join(lines)


def render_resources(rb: dict) -> str:
    """The machine-side panel (ISSUE 7): per-device HBM + headroom, host
    RSS/CPU, the buffer-attribution table, and the compile/retrace
    sub-block — one compact block per record."""
    lines = []
    devs = rb.get("devices") or []
    dev_bits = []
    for d in devs[:4]:
        if d.get("bytes_in_use") is None:
            continue
        bit = f"dev{d.get('id')}={d['bytes_in_use'] / 2**20:.0f}MiB"
        if d.get("headroom_frac") is not None:
            bit += f" ({100 * d['headroom_frac']:.0f}% free)"
        dev_bits.append(bit)
    host = rb.get("host") or {}
    host_bits = []
    if host.get("rss_bytes") is not None:
        host_bits.append(f"rss={host['rss_bytes'] / 2**20:.0f}MiB")
    if host.get("cpu_pct") is not None:
        host_bits.append(f"cpu={host['cpu_pct']:.0f}%")
    if host.get("threads") is not None:
        host_bits.append(f"threads={host['threads']}")
    lines.append("resources: "
                 + (" ".join(dev_bits) if dev_bits
                    else "(no device byte counters — CPU backend)")
                 + ("   host: " + " ".join(host_bits) if host_bits else ""))
    slots = rb.get("actor_slots") or {}
    if slots.get("rss_bytes"):
        rss = [f"{b / 2**20:.0f}" for b in slots["rss_bytes"]]
        cpu = ["-" if c is None else f"{c:.0f}"
               for c in slots.get("cpu_pct") or []]
        lines.append(f"  actor slots rss MiB: [{' '.join(rss)}]"
                     + (f"  cpu %: [{' '.join(cpu)}]" if cpu else ""))
    bufs = rb.get("buffers") or {}
    if bufs:
        top = sorted(bufs.items(), key=lambda kv: -kv[1])[:6]
        lines.append("  buffers: " + " ".join(
            f"{name}={b / 2**20:.0f}MiB" for name, b in top)
            + f"  total={rb.get('buffers_total', 0) / 2**20:.0f}MiB")
    comp = rb.get("compile")
    if comp:
        line = (f"  compile: total={comp.get('compiles_total', 0)} "
                f"({comp.get('compile_time_s_total', 0.0):.1f}s) "
                f"interval={comp.get('compiles', 0)} "
                f"retraces={comp.get('retraces_total', 0)}"
                + (" [warm]" if comp.get("warm") else " [warming up]"))
        aot = comp.get("aot") or {}
        if aot.get("missing"):
            line += f"  !! AOT buckets missing: {aot['missing']}"
        lines.append(line)
        last = comp.get("last_retrace")
        if comp.get("retraces_interval") and last:
            lines.append(f"  !! RETRACE {last.get('fn')} "
                         f"{(last.get('avals') or '')[:80]}")
    return "\n".join(lines)


def render_alerts(ab: dict) -> str:
    """The sentinel panel (ISSUE 7): rules active now + firings this
    interval; silent when everything is healthy."""
    active = ab.get("active") or []
    fired = ab.get("fired") or []
    if not active and not fired:
        return "alerts: none active"
    lines = [f"alerts ACTIVE: {' '.join(active)}"]
    for a in fired:
        bit = f"  -> FIRED {a.get('severity', '?').upper()} {a.get('rule')}"
        if a.get("value") is not None:
            bit += f" value={a['value']:.4g} bound={a.get('bound')}"
        if a.get("baseline") is not None:
            bit += f" baseline={a['baseline']:.4g}"
        lines.append(bit)
    return "\n".join(lines)


def newest_roofline(run_dir: str) -> Optional[dict]:
    """The newest roofline artifact next to the metrics stream (or in
    the working directory — where `make roofline` drops it)."""
    paths = [p for d in (run_dir, ".") for pat in
             ("ROOFLINE*.json", "roofline*.json")
             for p in glob.glob(os.path.join(d, pat))]
    if not paths:
        return None
    try:
        # getmtime inside the guard: a follow-mode dashboard can race a
        # `make roofline` rewrite (or a deletion) between glob and stat
        with open(max(set(paths), key=os.path.getmtime)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def costs_record(records: List[dict]) -> Optional[dict]:
    """The one-shot ``costs`` block from wherever in the stream it rode
    (the first record after the learner's first flush)."""
    for rec in reversed(records):
        if rec.get("costs"):
            return rec["costs"]
    return None


def newest_host_rows(run_dir: str) -> List[dict]:
    # O(tail) + rotation-aware: a near-cap host row file must not cost
    # a full parse per dashboard frame, and the instant between a
    # rotation's rename and its next write must not drop the rank
    rows = []
    for path in sorted(glob.glob(os.path.join(run_dir,
                                              "telemetry_host*.jsonl"))):
        row = read_last_jsonl_row(path)
        if row is not None:
            rows.append(row)
    return rows


def fleet_clock_offsets(run_dir: str):
    """Cross-host clock alignment from the fleet host rows (ISSUE 12):
    each rank's row carries a wall/monotonic anchor pair stamped when
    lockstep iteration 1's collective completed — a genuinely
    pod-synchronized instant — so ``offset[r] = anchor_r.wall -
    anchor_0.wall`` estimates rank r's wall-clock skew against rank 0.
    Returns ``({rank: offset_seconds}, actors_per_rank)``; empty when no
    anchored rows exist (pre-PR12 runs, fleet off, single-host)."""
    import re
    offsets = {}
    anchors = {}
    actors_per_rank = None
    for path in glob.glob(os.path.join(run_dir, "telemetry_host*.jsonl")):
        m = re.search(r"telemetry_host(\d+)\.jsonl$", path)
        if not m:
            continue
        row = read_last_jsonl_row(path)
        if row is None:
            continue
        a = row.get("clock_anchor")
        if a and a.get("wall") is not None:
            anchors[int(m.group(1))] = a
        if row.get("actors_per_rank"):
            actors_per_rank = int(row["actors_per_rank"])
    base = anchors.get(0)
    if base is not None:
        for r, a in anchors.items():
            offsets[r] = a["wall"] - base["wall"]
    return offsets, actors_per_rank


def plane_clock_offsets(run_dir: str) -> dict:
    """Per-PLANE clock offsets (ISSUE 19), generalizing the per-rank
    anchors: serve / replay-service processes stamp a ``proc`` header
    (plane, pid, wall/mono anchor) on their periodic rows, and a
    standalone ReplayService exchanges anchors with the lease board at
    announcement — its ``offset_est`` (seconds its wall clock runs
    AHEAD of the learner plane's, good to ±RTT/2) is what aligns its
    spans here. Planes without an exchange anchor at 0 (same-host wall
    clocks). Returns ``{spans-file basename: offset_seconds}``."""
    offsets = {}
    for name, pattern in (("spans_serve.jsonl", "serve_metrics.jsonl"),
                          ("spans_replay_service.jsonl",
                           "service_metrics_p*.jsonl")):
        for path in glob.glob(os.path.join(run_dir, pattern)):
            row = read_last_jsonl_row(path)
            anchor = ((row or {}).get("proc") or {}).get("clock_anchor")
            if anchor is not None:
                offsets[name] = float(anchor.get("offset_est") or 0.0)
    return offsets


def _span_file_rank(path: str, actors_per_rank) -> Optional[int]:
    """Which rank produced a spans file: host files carry it in the
    name; actor files carry the GLOBAL worker index, which maps back via
    the fleet rows' actors_per_rank (None = unknown, left unshifted)."""
    import re
    name = os.path.basename(path)
    m = re.match(r"spans_host(\d+)\.jsonl$", name)
    if m:
        return int(m.group(1))
    m = re.match(r"spans_p\d+_a(\d+)\.jsonl$", name)
    if m and actors_per_rank:
        return int(m.group(1)) // actors_per_rank
    return None


def export_chrome_trace(run_dir: str, out_path: str) -> int:
    """Merge every spans_*.jsonl under ``run_dir`` into one Chrome-trace
    JSON; returns the number of span events exported. When the run's
    fleet host rows carry clock anchors (ISSUE 12), every rank's spans
    are shifted onto rank 0's wall clock before the merge — one aligned
    Perfetto timeline with per-rank tracks instead of one skewed track
    per process."""
    from r2d2_tpu.telemetry import chrome_trace_events
    offsets, actors_per_rank = fleet_clock_offsets(run_dir)
    plane_offsets = plane_clock_offsets(run_dir)
    events = []
    n = 0
    for pid_index, path in enumerate(
            sorted(glob.glob(os.path.join(run_dir, "spans_*.jsonl")))):
        spans = parse_jsonl(path)
        n += len(spans)
        rank = _span_file_rank(path, actors_per_rank)
        shift = offsets.get(rank, 0.0) if rank is not None else 0.0
        # ISSUE 19: serve / replay-service plane spans align on the
        # anchor their process exchanged at lease announcement
        shift += plane_offsets.get(os.path.basename(path), 0.0)
        if shift:
            spans = [{**ev, "ts": ev["ts"] - shift} for ev in spans]
        pid = (spans[0].get("pid") if spans else None) or \
            os.path.basename(path)[len("spans_"):-len(".jsonl")]
        if rank is not None:
            pid = f"rank{rank}/{pid}"
        events.extend(chrome_trace_events(spans, pid, pid_index))
    with open(out_path, "w") as f:
        json.dump({"traceEvents": events,
                   "displayTimeUnit": "ms"}, f)
    return n


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--dir", default="models",
                   help="the run's save_dir (metrics/spans live there)")
    p.add_argument("--player", type=int, default=0)
    p.add_argument("--follow", action="store_true",
                   help="keep tailing and redraw per new record")
    p.add_argument("--interval", type=float, default=2.0,
                   help="poll cadence in follow mode")
    p.add_argument("--export-trace", default="",
                   help="write Chrome-trace JSON here (Perfetto-loadable) "
                        "and exit")
    args = p.parse_args(argv)

    if args.export_trace:
        n = export_chrome_trace(args.dir, args.export_trace)
        print(f"exported {n} spans from {args.dir!r} to "
              f"{args.export_trace!r}")
        return 0

    path = os.path.join(args.dir, f"metrics_player{args.player}.jsonl")
    last_len = -1
    while True:
        try:
            records = parse_jsonl(path)
        except FileNotFoundError:
            print(f"waiting for {path} ..." if args.follow
                  else f"no metrics stream at {path}")
            if not args.follow:
                return 1
            time.sleep(args.interval)
            continue
        if records and len(records) != last_len:
            last_len = len(records)
            frame = render_record(records[-1], newest_host_rows(args.dir),
                                  costs=costs_record(records),
                                  roofline=newest_roofline(args.dir))
            if args.follow and sys.stdout.isatty():
                sys.stdout.write("\x1b[2J\x1b[H")   # clear + home
            print(f"== {path} (record {len(records)}) ==")
            print(frame, flush=True)
            # the alert stream's newest firings (machine-readable side of
            # the record's 'alerts' panel; absent pre-PR7 or with the
            # pillar off)
            apath = os.path.join(args.dir,
                                 f"alerts_player{args.player}.jsonl")
            if os.path.exists(apath):
                for row in parse_jsonl(apath, limit=3):
                    print(f"  alert@t={row.get('t', 0):.0f}s "
                          f"{row.get('severity', '?')}: {row.get('rule')} "
                          f"value={row.get('value')}", flush=True)
        if not args.follow:
            return 0
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
