"""Parsers for the training telemetry file formats.

``parse_log`` reads ``train_player{i}.log``: key strings match the
reference's ReplayBuffer.log emissions exactly
(/root/reference/worker.py:220-234), which is also what the reference's
plot.py regexes expect (/root/reference/plot.py:33-48) — so this parser
reads logs from either framework. ``parse_jsonl`` reads the structured
stream TrainMetrics appends per log interval (``metrics_player{i}.jsonl``
and the multihost per-host ``telemetry_host{r}.jsonl`` rows share the
line format) — the machine-readable side tools/inspect.py and the e2e
bench consume.
"""

import json
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class ParsedLog:
    buffer_sizes: List[float] = field(default_factory=list)
    returns: List[float] = field(default_factory=list)        # per log interval
    return_counts: List[int] = field(default_factory=list)    # interval index
    losses: List[float] = field(default_factory=list)
    loss_counts: List[int] = field(default_factory=list)
    env_steps: List[float] = field(default_factory=list)
    training_steps: List[float] = field(default_factory=list)


def parse_log(path: str) -> ParsedLog:
    out = ParsedLog()
    count = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line.startswith("buffer size:"):
                out.buffer_sizes.append(float(line.split(":")[1]))
                count += 1
            elif line.startswith("average episode return:"):
                out.returns.append(float(line.split(":")[1]))
                out.return_counts.append(count)
            elif line.startswith("loss:"):
                out.losses.append(float(line.split(":")[1]))
                out.loss_counts.append(count)
            elif line.startswith("number of environment steps:"):
                out.env_steps.append(float(line.split(":")[1]))
            elif line.startswith("number of training steps:"):
                out.training_steps.append(float(line.split(":")[1]))
    return out


def parse_jsonl(path: str, limit: Optional[int] = None) -> List[dict]:
    """All records of a metrics/telemetry JSONL stream, oldest first
    (``limit`` keeps only the newest N). Partial trailing lines — a writer
    mid-append — are skipped, not fatal: the inspector tails live files."""
    out: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out[-limit:] if limit else out
