"""Parsers for the training telemetry file formats.

``parse_log`` reads ``train_player{i}.log``: key strings match the
reference's ReplayBuffer.log emissions exactly
(/root/reference/worker.py:220-234), which is also what the reference's
plot.py regexes expect (/root/reference/plot.py:33-48) — so this parser
reads logs from either framework. ``parse_jsonl`` reads the structured
stream TrainMetrics appends per log interval (``metrics_player{i}.jsonl``
and the multihost per-host ``telemetry_host{r}.jsonl`` rows share the
line format) — the machine-readable side tools/inspect.py and the e2e
bench consume.
"""

import json
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class ParsedLog:
    buffer_sizes: List[float] = field(default_factory=list)
    returns: List[float] = field(default_factory=list)        # per log interval
    return_counts: List[int] = field(default_factory=list)    # interval index
    losses: List[float] = field(default_factory=list)
    loss_counts: List[int] = field(default_factory=list)
    env_steps: List[float] = field(default_factory=list)
    training_steps: List[float] = field(default_factory=list)


def parse_log(path: str) -> ParsedLog:
    out = ParsedLog()
    count = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line.startswith("buffer size:"):
                out.buffer_sizes.append(float(line.split(":")[1]))
                count += 1
            elif line.startswith("average episode return:"):
                out.returns.append(float(line.split(":")[1]))
                out.return_counts.append(count)
            elif line.startswith("loss:"):
                out.losses.append(float(line.split(":")[1]))
                out.loss_counts.append(count)
            elif line.startswith("number of environment steps:"):
                out.env_steps.append(float(line.split(":")[1]))
            elif line.startswith("number of training steps:"):
                out.training_steps.append(float(line.split(":")[1]))
    return out


def learning_series(records: List[dict]) -> dict:
    """Time series of the ``learning`` block (ISSUE 5) across a metrics
    JSONL stream, aligned on the records that CARRY one (training pauses
    and pre-PR5 records are skipped, not holes). Keys: t, delta_q_stored/
    zero/recomputed, sample_age_p50/p95, replay_age_p50, grad_norm, plus
    td_p50/q_p50 — everything cli/plot.py --learning draws. Values are
    None where a record's block lacked that entry (e.g. ΔQ between
    interval steps)."""
    out = {k: [] for k in (
        "t", "training_steps", "delta_q_stored", "delta_q_zero",
        "delta_q_recomputed", "sample_age_p50", "sample_age_p95",
        "replay_age_p50", "grad_norm", "td_p50", "q_p50")}
    for r in records:
        lb = r.get("learning")
        if not lb:
            continue
        dq = lb.get("delta_q") or {}
        age = lb.get("sample_age") or {}
        rage = lb.get("replay_age") or {}
        gn = (lb.get("grad_norm") or {}).get("global") or {}
        out["t"].append(r.get("t"))
        out["training_steps"].append(r.get("training_steps"))
        out["delta_q_stored"].append(dq.get("stored"))
        out["delta_q_zero"].append(dq.get("zero"))
        out["delta_q_recomputed"].append(dq.get("recomputed"))
        out["sample_age_p50"].append(age.get("p50"))
        out["sample_age_p95"].append(age.get("p95"))
        out["replay_age_p50"].append(rage.get("p50"))
        out["grad_norm"].append(gn.get("mean"))
        out["td_p50"].append((lb.get("td_abs") or {}).get("p50"))
        out["q_p50"].append((lb.get("q_abs") or {}).get("p50"))
    return out


def replay_diag_series(records: List[dict]) -> dict:
    """Time series of the ``replay_diag`` block (ISSUE 10) across a
    metrics JSONL stream, aligned on the records that CARRY one (pre-PR10
    records and kill-switched runs are skipped, not holes) — the
    learning_series contract. Keys: t, training_steps, ess_frac,
    max_mean_ratio, frac_at_max, active_leaves, never_sampled_frac,
    evicted, mean_lifetime, starved_frac, max_share — everything
    cli/plot.py --replay-diag draws. Values are None where a record's
    block lacked that entry (e.g. evictions before the first ring
    wrap)."""
    out = {k: [] for k in (
        "t", "training_steps", "ess_frac", "max_mean_ratio",
        "frac_at_max", "active_leaves", "never_sampled_frac", "evicted",
        "mean_lifetime", "starved_frac", "max_share")}
    for r in records:
        rd = r.get("replay_diag")
        if not rd:
            continue
        tree = rd.get("tree") or {}
        ev = rd.get("evictions") or {}
        ln = rd.get("lanes") or {}
        out["t"].append(r.get("t"))
        out["training_steps"].append(r.get("training_steps"))
        out["ess_frac"].append(tree.get("ess_frac"))
        out["max_mean_ratio"].append(tree.get("max_mean_ratio"))
        out["frac_at_max"].append(tree.get("frac_at_max"))
        out["active_leaves"].append(tree.get("active_leaves"))
        out["never_sampled_frac"].append(ev.get("never_sampled_frac"))
        out["evicted"].append(ev.get("evicted"))
        out["mean_lifetime"].append(ev.get("mean_lifetime"))
        out["starved_frac"].append(ln.get("starved_frac"))
        out["max_share"].append(ln.get("max_share"))
    return out


def fleet_series(records: List[dict]) -> dict:
    """Time series of the ``fleet`` block (ISSUE 12) across a metrics (or
    host-row) JSONL stream, aligned on the records that CARRY one
    (single-host records and kill-switched runs are skipped, not holes)
    — the learning_series contract. Keys: t, training_steps, wait_frac,
    skew, straggler_rank, divergence, step_time_mean_ms,
    step_time_max_ms, per_rank_ms (one list per record), max_age_s —
    everything cli/plot.py --fleet draws. Values are None where a
    record's block lacked that entry (e.g. host-row ages on a rank > 0
    row)."""
    out = {k: [] for k in (
        "t", "training_steps", "wait_frac", "skew", "straggler_rank",
        "divergence", "step_time_mean_ms", "step_time_max_ms",
        "per_rank_ms", "max_age_s")}
    for r in records:
        fb = r.get("fleet")
        if not fb:
            continue
        ls = fb.get("lockstep") or {}
        st = fb.get("step_time") or {}
        env = fb.get("env_steps") or {}
        hr = fb.get("host_rows") or {}
        out["t"].append(r.get("t"))
        out["training_steps"].append(r.get("training_steps"))
        out["wait_frac"].append(ls.get("wait_frac"))
        out["skew"].append(st.get("skew"))
        out["straggler_rank"].append(st.get("straggler_rank"))
        out["divergence"].append(env.get("divergence"))
        out["step_time_mean_ms"].append(st.get("mean_ms"))
        out["step_time_max_ms"].append(st.get("max_ms"))
        out["per_rank_ms"].append(st.get("per_rank_ms"))
        out["max_age_s"].append(hr.get("max_age_s"))
    return out


def serve_series(records: List[dict]) -> dict:
    """Time series of the ``serving`` block (ISSUE 13) across a metrics
    JSONL stream (``metrics_player{p}.jsonl`` in served-training runs, or
    the standalone server's ``serve_metrics.jsonl``), aligned on the
    records that CARRY one — the learning_series contract. Keys: t,
    training_steps, requests, latency_p50_ms/p95_ms/p99_ms, fill_mean,
    full_frac, deadline_frac, starved_frac, clients_active, connects,
    reconnects, disconnects, evictions, timeouts, expired. Values are
    None where a record's block lacked that entry."""
    out = {k: [] for k in (
        "t", "training_steps", "requests", "latency_p50_ms",
        "latency_p95_ms", "latency_p99_ms", "fill_mean", "full_frac",
        "deadline_frac", "starved_frac", "clients_active", "connects",
        "reconnects", "disconnects", "evictions", "timeouts", "expired")}
    for r in records:
        sv = r.get("serving")
        if not sv:
            continue
        lat = sv.get("latency") or {}
        batch = sv.get("batch") or {}
        clients = sv.get("clients") or {}
        out["t"].append(r.get("t"))
        out["training_steps"].append(r.get("training_steps"))
        out["requests"].append(sv.get("requests"))
        out["latency_p50_ms"].append(lat.get("p50_ms"))
        out["latency_p95_ms"].append(lat.get("p95_ms"))
        out["latency_p99_ms"].append(lat.get("p99_ms"))
        out["fill_mean"].append(batch.get("fill_mean"))
        out["full_frac"].append(batch.get("full_frac"))
        out["deadline_frac"].append(batch.get("deadline_frac"))
        out["starved_frac"].append(batch.get("starved_frac"))
        out["clients_active"].append(clients.get("active"))
        out["connects"].append(clients.get("connects"))
        out["reconnects"].append(clients.get("reconnects"))
        out["disconnects"].append(clients.get("disconnects"))
        out["evictions"].append(clients.get("evictions"))
        out["timeouts"].append(sv.get("timeouts"))
        out["expired"].append(sv.get("expired"))
    return out


def alerts_series(path: str, limit: Optional[int] = None) -> dict:
    """Time series of an ``alerts_player{p}.jsonl`` stream (ISSUE 7) —
    one entry per FIRED alert, oldest first, with ``parse_jsonl``'s
    partial-line tolerance (the sentinel tails live files). Keys: t,
    training_steps, env_steps, rule, severity, value, bound."""
    out = {k: [] for k in ("t", "training_steps", "env_steps", "rule",
                           "severity", "value", "bound")}
    for row in parse_jsonl(path, limit=limit):
        for k in out:
            out[k].append(row.get(k))
    return out


def resources_series(records: List[dict]) -> dict:
    """Time series of the ``resources`` block (ISSUE 7) across a metrics
    JSONL stream, aligned on the records that CARRY one (pre-PR7 records
    and kill-switched runs are skipped, not holes) — the same contract as
    :func:`learning_series`. Keys: t, training_steps, hbm_headroom (the
    min across devices), bytes_in_use (summed across devices), host_rss,
    host_cpu_pct, buffers_total, compiles, compile_time_s, retraces
    (cumulative), and alerts_active (count, from the sibling ``alerts``
    block when present). Values are None where a record's block lacked
    that entry (e.g. device counters on a CPU backend)."""
    out = {k: [] for k in (
        "t", "training_steps", "hbm_headroom", "bytes_in_use", "host_rss",
        "host_cpu_pct", "buffers_total", "compiles", "compile_time_s",
        "retraces", "alerts_active")}
    for r in records:
        rb = r.get("resources")
        if not rb:
            continue
        in_use = [d.get("bytes_in_use") for d in rb.get("devices") or []]
        in_use = [b for b in in_use if b is not None]
        host = rb.get("host") or {}
        comp = rb.get("compile") or {}
        alerts = r.get("alerts") or {}
        out["t"].append(r.get("t"))
        out["training_steps"].append(r.get("training_steps"))
        out["hbm_headroom"].append(rb.get("hbm_headroom_frac_min"))
        out["bytes_in_use"].append(sum(in_use) if in_use else None)
        out["host_rss"].append(host.get("rss_bytes"))
        out["host_cpu_pct"].append(host.get("cpu_pct"))
        out["buffers_total"].append(rb.get("buffers_total"))
        out["compiles"].append(comp.get("compiles_total"))
        out["compile_time_s"].append(comp.get("compile_time_s_total"))
        out["retraces"].append(comp.get("retraces_total"))
        out["alerts_active"].append(len(alerts.get("active") or [])
                                    if alerts else None)
    return out


def parse_jsonl(path: str, limit: Optional[int] = None) -> List[dict]:
    """All records of a metrics/telemetry JSONL stream, oldest first
    (``limit`` keeps only the newest N). Partial trailing lines — a writer
    mid-append — are skipped, not fatal: the inspector tails live files."""
    out: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out[-limit:] if limit else out
