"""Offline tools: log parsing/plotting, checkpoint evaluation, genetic search."""
